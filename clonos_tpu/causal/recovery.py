"""Causal recovery: the standby-replay protocol.

Capability parity with the reference's recovery core
(flink-runtime .../causal/recovery/ — RecoveryManager.java:37-60 state
machine Standby -> WaitingConnections -> WaitingDeterminants -> Replaying ->
Running, with synchronized event dispatch :66-108; WaitingDeterminantsState
sends InFlightLogRequest + DeterminantRequest events :126-155 and merges
responses; ReplayingState rebuilds output buffers from BufferBuilt
determinants :136-215; LogReplayerImpl serves recorded values back and
asserts post-replay log-length equality :121-133) — re-designed TPU-first:

- The FSM stays on the **host** (it runs once per failure, not per record),
  but replay itself is **one ``lax.scan`` on device**: the lost epochs'
  input batches (from the upstream in-flight rings) and the failed task's
  determinant tensor (merged from downstream replicas) are stacked along a
  steps axis and the vertex's operator is scanned over them. The JVM's
  record-at-a-time replay loop becomes a single compiled program — this is
  where the >=10x replay-rate target lands (BASELINE.md).
- Determinants arrive as the packed ``int32[n, 8]`` rows the log already
  stores; because the executor's per-step layout is fixed (TIMESTAMP, RNG,
  ORDER, BUFFER_BUILT — executor.DETS_PER_STEP = 4), the replayer locates
  the ``[steps, 4, lanes]`` sync blocks and reads payload lanes directly.
- Output reconstruction: the replayed operator re-emits its output batches;
  the replayer verifies each batch's record count against the recorded
  BUFFER_BUILT determinant (the bit-identical buffer-cut check,
  PipelinedSubpartition.buildAndLogBuffer:536-571) and *discards* the
  batches — downstream already consumed them (the dedup the reference gets
  from numBuffersToSkip).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.operators import OpContext, Operator, TwoInputOperator
from clonos_tpu.api.records import RecordBatch
from clonos_tpu.causal import determinant as det
from clonos_tpu.causal import log as clog
from clonos_tpu.obs import get_tracer as _get_tracer


class RecoveryState(enum.Enum):
    STANDBY = "standby"
    WAITING_CONNECTIONS = "waiting_connections"
    WAITING_DETERMINANTS = "waiting_determinants"
    REPLAYING = "replaying"
    RUNNING = "running"


class RecoveryError(RuntimeError):
    pass


class AuditDivergenceError(RecoveryError):
    """A replayed epoch's recomputed audit digest does not match the
    sealed ledger entry — the exactly-once replay contract is violated
    (raised only under ``observability.audit.on-divergence = abort``)."""


class AuditValidator:
    """Recovery-time half of the epoch audit ledger (obs/audit.py).

    After the causal replay has patched the failed subtasks back into the
    live carry, the validator recomputes each replayed epoch's digest
    from the SAME extraction path the live seal used
    (``LocalExecutor.epoch_window`` + ``digest_epoch_window``) and
    compares it against the persisted ledger — turning "replay is
    bit-identical" from a test-time hope into a runtime invariant. Every
    epoch emits a ``recovery.audit.match`` / ``recovery.audit.divergence``
    / ``recovery.audit.missing`` instant into the active recovery trace;
    the first divergence names the epoch and channel (which subtask's
    determinant log or which vertex's output ring went off-script) and,
    under the ``abort`` policy, raises :class:`AuditDivergenceError`.
    """

    def __init__(self, executor, ledger_entries: Sequence[dict],
                 on_divergence: str = "warn"):
        self.executor = executor
        # last-wins per epoch: a rebuilt runner appends fresh seals for
        # post-recovery epochs to the same durable ledger
        self.ledger: Dict[int, dict] = {
            int(e["epoch"]): e for e in ledger_entries}
        self.on_divergence = on_divergence
        #: running totals — still accurate when the abort policy throws
        #: mid-validation (the caller's metrics read these, not the
        #: return value)
        self.stats: Dict[str, int] = {"match": 0, "divergence": 0,
                                      "missing": 0}

    def validate(self, epochs: Sequence[int]) -> Dict[str, int]:
        """Validate the given replayed (closed) epochs against the
        ledger. Returns ``{"match": n, "divergence": n, "missing": n}``;
        raises under the abort policy after emitting the divergence
        instant (the flight recorder keeps the evidence either way)."""
        from clonos_tpu.obs import audit as _audit
        from clonos_tpu.obs.digest import EpochDigest, diff as _diff
        tr = _get_tracer()
        stats = self.stats
        for e in epochs:
            e = int(e)
            recomputed = _audit.digest_epoch_window(
                e, self.executor.epoch_window(e))
            entry = self.ledger.get(e)
            if entry is None:
                stats["missing"] += 1
                tr.event("recovery.audit.missing", epoch=e)
                continue
            d = _diff(EpochDigest.from_entry(entry), recomputed)
            if d is None:
                stats["match"] += 1
                tr.event("recovery.audit.match", epoch=e,
                         channels=len(recomputed.channels),
                         records=recomputed.record_count())
            else:
                stats["divergence"] += 1
                channel, reason = d
                tr.event("recovery.audit.divergence", epoch=e,
                         channel=channel, reason=reason)
                # Flight-recorder trigger: the replayed epoch went
                # off-script — bundle the evidence before the abort
                # policy (possibly) tears recovery down. No-op when
                # the incident plane is disabled.
                from clonos_tpu.obs.incident import get_incidents
                get_incidents().signal("audit.divergence", epoch=e,
                                       channel=channel, reason=reason,
                                       source="recovery-validator")
                if self.on_divergence == "abort":
                    raise AuditDivergenceError(
                        f"epoch {e} channel {channel}: {reason} — replay "
                        f"did not reproduce the original execution")
        return stats

    def recompute_entries(self, epochs: Sequence[int]) -> List[dict]:
        """Recompute the given epochs' digests from the CURRENT carry
        and return them as ledger entries (``EpochDigest.to_entry``
        dicts) WITHOUT validating against the persisted ledger — the
        raw material for a ``diff_ledgers`` comparison between two
        recovery modes (bench proves the overlapped finalize pipeline
        bit-identical to a sequential-recovery control this way:
        ``diff_ledgers(seq_entries, overlap_entries) == []``)."""
        from clonos_tpu.obs import audit as _audit
        return [_audit.digest_epoch_window(
                    int(e), self.executor.epoch_window(int(e))).to_entry()
                for e in epochs]


@dataclasses.dataclass
class ReplayPlan:
    """Everything a standby needs to replay one failed subtask."""

    vertex_id: int
    subtask: int                    # subtask index within the vertex
    flat_subtask: int               # global flat id (log row)
    from_epoch: int                 # first lost epoch (checkpoint + 1 ...)
    #: the lost input batches: a LIST of block_steps-sized chunks (each a
    #: RecordBatch [CH, cap] for single-input vertices, a (left, right)
    #: pair for TwoInputOperator vertices), or a legacy stacked [n, cap]
    #: batch, or None for self-generating sources. Chunked form keeps every
    #: device program shape-static so the whole replay runs on programs
    #: compiled at job start (warm standby — no XLA in the failure path).
    input_steps: Optional[Any]
    det_rows: np.ndarray            # int32[m, lanes] merged determinant rows
    det_start: int                  # absolute offset of det_rows[0]
    checkpoint_op_state: Any        # failed vertex's op state [P, ...] slice
    n_steps: int                    # lost supersteps to replay
    #: False when the determinant rows were synthesized rather than
    #: recovered from replicas (pure-sink recovery: no downstream holds the
    #: sink's log; its inputs replay exactly but its own output cuts have
    #: no recorded value to check against).
    verify_outputs: bool = True
    #: Device-resident determinant stream for the clean fast path
    #: (consistent replica, pure sync rows): (times, rngs, expected)
    #: int32 device arrays padded to the replayer's ``pad_steps``. When
    #: set, ``det_rows`` stays empty — the multi-MB log body never
    #: crosses the host link (it was parsed ON DEVICE; cluster
    #: _device_parse_fn), which was the dominant warm-recovery cost on a
    #: tunneled backend.
    det_device: Optional[Any] = None


@dataclasses.dataclass
class ReplayResult:
    op_state: Any                   # rebuilt [1, ...] subtask state slice
    rebuilt_log_rows: np.ndarray    # regenerated determinant rows (sync
                                    # blocks re-derived, async rows spliced
                                    # back at their recorded positions)
    emit_counts: np.ndarray         # [n] replayed output batch cuts (host)
    expected_emits: np.ndarray      # [n] recorded BUFFER_BUILT values
    #: the replayed operator's rebuilt output batches as a list of
    #: block-sized chunks [CH, out_cap] (last chunk may be shorter) — the
    #: reconstruction of the failed producer's in-flight log shard
    #: (reference PipelinedSubpartition.buildAndLogBuffer:536-599: the
    #: standby re-cuts bit-identical buffers and re-logs them). Chunked so
    #: the ring write-back reuses prewarmed fixed-shape programs.
    out_chunks: Optional[List[RecordBatch]]
    records_replayed: int
    #: async determinants recovered from the log: (step_index, determinant)
    #: fired before superstep ``step_index`` of the replay range (reference
    #: LogReplayerImpl.triggerAsyncEvent:102 — the control plane re-fires
    #: their effects; services replay their values).
    async_events: List[Tuple[int, det.Determinant]] = dataclasses.field(
        default_factory=list)
    #: wall-clock breakdown of the replay call (parse / device / rebuild).
    phase_ms: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: True when rebuilt_log_rows is a view of the recovered rows (the
    #: clean fast path, where verify() already establishes equality) —
    #: callers must not "re-verify" it against the same buffer.
    rebuilt_is_view: bool = False
    #: Deferred-sync replay (``replay(plan, defer_sync=True)``): nothing
    #: crossed the host link — ``emit_counts``/``expected_emits`` are
    #: device arrays, ``records_replayed`` is -1 until the cluster's
    #: final packed read resolves it, and verification is the device
    #: flag ``verify_ok_d`` (folded into that same read). On a tunneled
    #: backend every host sync costs a ~100ms round-trip, so the warm
    #: failure path defers them all into one.
    deferred: bool = False
    verify_ok_d: Optional[Any] = None
    consumed_d: Optional[Any] = None

    def verify(self) -> None:
        """Post-replay equality asserts (reference LogReplayerImpl:127,
        ReplayingState:196): every replayed output cut must equal the
        recorded one."""
        got = np.asarray(self.emit_counts)
        want = np.asarray(self.expected_emits)
        if not np.array_equal(got, want):
            bad = np.nonzero(got != want)[0]
            raise RecoveryError(
                f"replay diverged: output batch cuts differ at replayed "
                f"steps {bad.tolist()} (got {got[bad].tolist()}, recorded "
                f"{want[bad].tolist()})")


def plan_restore_nbytes(plan: ReplayPlan) -> int:
    """Bytes this plan's shard-local restore moves: the failed subtask's
    slice of the checkpointed vertex state (one row of the [P, ...]
    pytree — healthy subtasks' rows stay in their live buffers), the
    recovered determinant stream, and the replayed input windows. The
    per-shard numerator of RecoveryReport.restore_bytes; compare against
    checkpoint.carry_nbytes of the full snapshot to see what a global
    rollback would have moved instead."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(plan.checkpoint_op_state):
        n0 = getattr(leaf, "shape", (1,))[0] if getattr(
            leaf, "ndim", 0) > 0 else 1
        total += int(getattr(leaf, "nbytes", 0)) // max(1, n0)
    if plan.det_rows is not None and getattr(plan.det_rows, "size", 0):
        total += int(plan.det_rows.nbytes)
    elif plan.det_device is not None:
        total += sum(int(np.prod(x.shape)) * 4
                     for x in plan.det_device if hasattr(x, "shape"))
    if plan.input_steps is not None:
        for leaf in jax.tree_util.tree_leaves(plan.input_steps):
            total += int(getattr(leaf, "nbytes", 0))
    return total


class LogReplayer:
    """Serves recorded determinants back and drives the on-device replay
    (reference LogReplayer/LogReplayerImpl.java:36-157). Replay runs the
    operator's **block form** over the lost step range — the same
    step-batched kernels as the live path, so a multi-thousand-step replay
    is a handful of fused programs, not a per-step loop (this is where the
    >=10x replay-rate target lands, BASELINE.md)."""

    def __init__(self, operator: Operator, parallelism: int,
                 block_steps: int = 512, in_slot_keys=None,
                 pad_steps: Optional[int] = None):
        self.operator = operator
        self.parallelism = parallelism
        self.block_steps = block_steps
        #: fixed upper bound to pad the uploaded time/rng streams to (the
        #: recoverable window, e.g. the in-flight ring depth): keeps the
        #: tslice program's input shape INDEPENDENT of n_steps, so the
        #: prewarmed executable serves every failure instead of
        #: recompiling on the failure path when n differs from the drill.
        self.pad_steps = (-(-pad_steps // block_steps) * block_steps
                          if pad_steps else None)
        #: static [1, cap] input-slot keys when the failed subtask's input
        #: edge is statically routed (routing.StaticRoutePlan) — replay
        #: then uses the same fast static-gather aggregation as the live
        #: block program.
        self.in_slot_keys = in_slot_keys
        # Share compiled replay programs across LogReplayer instances for
        # the same (operator, block shape, slot keys): a later failure of
        # the same vertex must not pay a retrace (the jit cache is
        # per-wrapper, and RecoveryManagers are built per failure).
        cache = operator.__dict__.setdefault("_replay_jit_cache", {})
        key = (parallelism, block_steps,
               None if in_slot_keys is None
               else np.asarray(in_slot_keys).tobytes())
        if key not in cache:
            cache[key] = jax.jit(self._replay_block)
        self._jit_block = cache[key]
        skey = ("tslice", block_steps)
        if skey not in cache:
            cache[skey] = jax.jit(lambda v, lo: jax.lax.dynamic_slice(
                v, (lo,), (block_steps,)))
        self._jit_tslice = cache[skey]

    def _replay_block(self, op_state, batches, times, rngs, subtask,
                      consumed_in):
        """One block of replay: state has leading dim 1 (the failed subtask
        alone); operators are written over an arbitrary leading P dim, so
        the same block code replays one subtask that ran as one lane of P.
        ``consumed_in`` is the running consumed-record total — accumulated
        INSIDE the program so the loop's end needs no extra eager
        stack/sum dispatches (each costs a ~9ms tunnel round-trip)."""
        from clonos_tpu.api.operators import BlockContext
        lift = lambda b: jax.tree_util.tree_map(lambda x: x[:, None], b)
        bctx = BlockContext(
            times=times, rng_bits=rngs, epoch=jnp.zeros((), jnp.int32),
            step0=jnp.zeros((), jnp.int32), subtask=subtask[None])
        if isinstance(self.operator, TwoInputOperator):
            left, right = batches
            new_state, out = self.operator.process_block(
                op_state, (lift(left), lift(right)), bctx)
            consumed = left.count().sum() + right.count().sum()
        elif self.in_slot_keys is not None and hasattr(
                self.operator, "process_block_static_keys"):
            new_state, out = self.operator.process_block_static_keys(
                op_state, lift(batches), bctx, self.in_slot_keys)
            consumed = batches.count().sum()
        else:
            new_state, out = self.operator.process_block(
                op_state, lift(batches), bctx)
            consumed = batches.count().sum()
        # Drop the singleton P dim: out [k, 1, cap] -> [k, cap].
        out = jax.tree_util.tree_map(lambda x: x[:, 0], out)
        return (new_state, out, out.count(),
                consumed_in + consumed.astype(jnp.int32))

    #: per-step sync row layout (must match executor.DETS_PER_STEP appends)
    LAYOUT = (det.TIMESTAMP, det.RNG, det.ORDER, det.BUFFER_BUILT)

    def _parse(self, rows: np.ndarray, n: int):
        """Tag-aware parse: locate the n per-step sync blocks (anchored at
        TIMESTAMP rows) and classify everything between them as async
        determinant rows (host-appended between supersteps)."""
        k = len(self.LAYOUT)
        tags = rows[:, det.LANE_TAG]
        ts_idx = det.sync_anchors(rows)
        if len(ts_idx) < n:
            raise RecoveryError(
                f"determinant log too short: need {n} superstep blocks, "
                f"have {len(ts_idx)}")
        ts_idx = ts_idx[:n]
        for i, tag in enumerate(self.LAYOUT[1:], start=1):
            pos = ts_idx + i
            if (pos >= rows.shape[0]).any() or not (tags[pos] == tag).all():
                raise RecoveryError(
                    "determinant stream has unexpected layout (corrupt or "
                    f"misaligned response at sync lane {i})")
        sync_pos = (ts_idx[:, None] + np.arange(k)[None, :]).ravel()
        used = int(sync_pos.max()) + 1 if n > 0 else 0
        # Trailing async rows (appended after the last replayed step).
        while used < rows.shape[0] and not (
                tags[used] == det.TIMESTAMP
                and rows[used, det.LANE_RC] == 0):
            used += 1
        mask = np.ones(used, bool)
        mask[sync_pos] = False
        async_pos = np.nonzero(mask)[0]
        async_step = np.searchsorted(ts_idx, async_pos)
        async_events = [(int(async_step[j]),
                         det.Determinant.unpack(rows[async_pos[j]]))
                        for j in range(len(async_pos))]
        return ts_idx, int(used), async_events

    def replay(self, plan: ReplayPlan,
               defer_sync: bool = False) -> ReplayResult:
        """Drive the replay off either determinant-stream source:
        host rows (``plan.det_rows``, parsed/spliced here) or the
        device-resident stream (``plan.det_device`` — clean path: no log
        body on the host, no parse, no splice; only emit counts and
        expected cuts, a few KB, ever transfer).

        ``defer_sync`` (device stream only): dispatch everything and
        transfer NOTHING — the output-cut verification becomes a device
        flag and the consumed total stays a device scalar, both folded
        into the cluster's single end-of-recovery read (ReplayResult
        fields ``verify_ok_d`` / ``consumed_d``)."""
        import time as _time
        phases: Dict[str, float] = {}
        t_last = _time.monotonic()

        def _clock(name: str) -> None:
            nonlocal t_last
            now = _time.monotonic()
            phases[name] = phases.get(name, 0.0) + (now - t_last) * 1e3
            t_last = now

        n = plan.n_steps
        k = len(self.LAYOUT)
        dev = plan.det_device is not None
        if dev:
            if not plan.verify_outputs:    # pragma: no cover
                raise RecoveryError(
                    "device stream requires verifiable (non-synthesized) "
                    "recovery")
            t_dev, r_dev, expected_d = plan.det_device
            rows = np.zeros((0, det.NUM_LANES), np.int32)
            ts_idx = np.zeros((0,), np.int64)
            used = 0
            async_events: List[Tuple[int, Any]] = []
            times_np = rngs_np = expected = None
        else:
            rows = np.asarray(plan.det_rows)
            ts_idx, used, async_events = self._parse(rows, n)
        _clock("parse")
        if not dev:
            times_np = rows[ts_idx, det.LANE_P + 1].astype(np.int32)
            rngs_np = rows[ts_idx + 1, det.LANE_P].astype(np.int32)
            expected = rows[ts_idx + 3, det.LANE_P].astype(np.int32)

        # Chunked inputs arrive as a plain list (one element per replay
        # block); legacy stacked inputs are a RecordBatch or a (left,
        # right) tuple of stacked RecordBatches.
        chunked = isinstance(plan.input_steps, list)
        inputs = None if chunked else plan.input_steps
        if plan.input_steps is None:
            # Source vertex: regenerates its records; inputs are empty.
            cap = self.operator.out_capacity or 1
            zc = jnp.zeros((self.block_steps, cap), jnp.int32)
            self._zero_chunk = RecordBatch(
                zc, zc, zc, jnp.zeros((self.block_steps, cap), jnp.bool_))

        state = jax.tree_util.tree_map(
            lambda x: x[plan.subtask][None], plan.checkpoint_op_state)
        subtask = jnp.asarray(plan.subtask, jnp.int32)
        out_chunks: List[Any] = []
        emit_chunks: List[jnp.ndarray] = []
        consumed_acc = jnp.zeros((), jnp.int32)
        ch = self.block_steps
        if not dev:
            # One h2d of the whole (pad-extended) time/rng streams;
            # per-chunk views are prewarmed dynamic slices — each h2d
            # costs a full tunnel round-trip, so per-chunk uploads
            # dominate warm replay. (The device stream arrives already
            # padded to pad_steps.)
            npad = -(-max(n, 1) // ch) * ch
            if self.pad_steps is not None and npad <= self.pad_steps:
                npad = self.pad_steps
            t_all = np.full((npad,), times_np[n - 1] if n else 0, np.int32)
            r_all = np.full((npad,), rngs_np[n - 1] if n else 0, np.int32)
            t_all[:n] = times_np[:n]
            r_all[:n] = rngs_np[:n]
            t_dev = jnp.asarray(t_all)
            r_dev = jnp.asarray(r_all)
        lo = 0
        ci = 0
        while lo < n:
            hi = min(lo + ch, n)
            kk = hi - lo
            # Tail blocks: pad-safe operators run the full fixed block
            # shape with repeated time/rng and (already all-invalid) pad
            # inputs, so the warm standby's prewarmed program serves every
            # n; pad-unsafe operators (pure generators) run the exact tail
            # and pay one small compile. The device stream is pad-safe by
            # construction (the clean-path guard requires it).
            pad = dev or (kk < ch and self.operator.replay_pad_safe
                          and (chunked or plan.input_steps is None))
            if chunked:
                chunk = plan.input_steps[ci]
            elif plan.input_steps is None:
                chunk = self._zero_chunk
            else:
                chunk = jax.tree_util.tree_map(lambda x: x[lo:hi], inputs)
            if kk < ch and not pad and (chunked or
                                        plan.input_steps is None):
                chunk = jax.tree_util.tree_map(lambda x: x[:kk], chunk)
            if pad or kk == ch:
                lo_j = jnp.asarray(lo, jnp.int32)
                t_in = self._jit_tslice(t_dev, lo_j)
                r_in = self._jit_tslice(r_dev, lo_j)
            else:
                t_in = jnp.asarray(times_np[lo:hi])
                r_in = jnp.asarray(rngs_np[lo:hi])
            state, out, counts, consumed_acc = self._jit_block(
                state, chunk, t_in, r_in, subtask, consumed_acc)
            out_chunks.append(out)
            emit_chunks.append(counts)
            lo = hi
            ci += 1
        final_state = state
        if defer_sync:
            if not dev:    # pragma: no cover - cluster guards eligibility
                raise RecoveryError(
                    "defer_sync requires the device-resident determinant "
                    "stream (host-row plans must parse on the host)")
            emit_d = jnp.concatenate(emit_chunks, axis=0)[:n]
            exp_d = expected_d[:n]
            ok_d = jnp.all(emit_d == exp_d)
            _clock("device_replay")
            return ReplayResult(
                op_state=final_state,
                rebuilt_log_rows=rows[:0], emit_counts=emit_d,
                expected_emits=exp_d,
                out_chunks=out_chunks if out_chunks else None,
                records_replayed=-1, async_events=[],
                phase_ms=phases, rebuilt_is_view=True,
                deferred=True, verify_ok_d=ok_d, consumed_d=consumed_acc)
        # ONE concat dispatch + ONE d2h for the emit counts, the
        # in-program consumed total, and (device path) the expected cuts
        # (separate eager stack/sum/transfer calls each cost a tunnel
        # round-trip).
        tail = [consumed_acc.reshape(1)]
        if dev:
            tail.append(expected_d[:max(n, 1)])
        packed = jnp.concatenate(emit_chunks + tail, axis=0)
        packed_np = np.asarray(packed)             # d2h sync point
        n_emit = sum(int(c.shape[0]) for c in emit_chunks)
        emit_np = packed_np[:n_emit][:n]
        consumed_total = int(packed_np[n_emit])
        if dev:
            expected = packed_np[n_emit + 1:][:n]
        _clock("device_replay")

        # Regenerate the determinant rows the replayed run would log — the
        # rebuilt log must extend the recovered one bit-for-bit. Sync blocks
        # are re-derived from the replay; async rows are spliced back at
        # their recorded positions (append-even-during-replay invariant).
        # Clean case (no async rows, real recovered determinants): the
        # re-derived sync values differ from the recorded rows only in the
        # BUFFER_BUILT payload, and verify() checks exactly that equality —
        # so the rebuilt stream IS the recovered prefix, no copy needed.
        rebuilt_is_view = not async_events and plan.verify_outputs
        if rebuilt_is_view:
            rebuilt = rows[:used]
        else:
            blocks = np.zeros((n, k, det.NUM_LANES), np.int32)
            blocks[:, 0, det.LANE_TAG] = det.TIMESTAMP
            blocks[:, 0, det.LANE_P] = np.where(times_np < 0, -1, 0)
            blocks[:, 0, det.LANE_P + 1] = times_np
            blocks[:, 1, det.LANE_TAG] = det.RNG
            blocks[:, 1, det.LANE_P] = rngs_np
            blocks[:, 2, det.LANE_TAG] = det.ORDER
            blocks[:, 3, det.LANE_TAG] = det.BUFFER_BUILT
            blocks[:, 3, det.LANE_P] = emit_np
            rebuilt = rows[:used].copy()
            sync_pos = (ts_idx[:, None] + np.arange(k)[None, :])  # [n, k]
            rebuilt[sync_pos.ravel()] = blocks.reshape(
                n * k, det.NUM_LANES)

        consumed = (consumed_total if plan.input_steps is not None
                    else int(emit_np.sum()))
        _clock("rebuild_rows")
        return ReplayResult(
            op_state=final_state, rebuilt_log_rows=rebuilt,
            emit_counts=emit_np, expected_emits=expected,
            out_chunks=out_chunks if out_chunks else None,
            records_replayed=consumed, async_events=async_events,
            phase_ms=phases, rebuilt_is_view=rebuilt_is_view)


class RecoveryManager:
    """Host-side per-failed-subtask recovery FSM (reference
    RecoveryManager.java). Event methods mirror the reference's
    notifications; the cluster runner drives them in order and observers
    (tests, metrics) can watch ``state`` transitions."""

    def __init__(self, vertex_id: int, subtask: int, flat_subtask: int,
                 replayer: LogReplayer):
        self.vertex_id = vertex_id
        self.subtask = subtask
        self.flat_subtask = flat_subtask
        self.replayer = replayer
        self.state = RecoveryState.STANDBY
        self._pending_inputs: Dict[int, bool] = {}
        self._pending_outputs: Dict[int, bool] = {}
        self._state_restored = False
        self._responses: List[Tuple[np.ndarray, int]] = []
        self._expected_responses = 0
        self._expected_set = False
        self.plan: Optional[ReplayPlan] = None
        self.result: Optional[ReplayResult] = None
        self.transitions: List[RecoveryState] = [self.state]
        #: transition observers: ``fn(kind, **fields)`` on every FSM
        #: state change — the verify conformance layer's observation
        #: surface (kind is the entered state's name).
        self.transition_observers: List = []

    def _goto(self, s: RecoveryState) -> None:
        self.state = s
        self.transitions.append(s)
        for fn in self.transition_observers:
            fn(s.name, flat=self.flat_subtask)
        tr = _get_tracer()
        if tr.enabled:
            # FSM transitions as instants (reference RecoveryManager
            # logs each state change) — the fine-grained layer under
            # the recovery.* phase spans the cluster runner emits.
            tr.event("recovery.fsm", state=s.name, flat=self.flat_subtask,
                     vertex=self.vertex_id, subtask=self.subtask)
        from clonos_tpu.obs import get_timeline
        tl = get_timeline()
        if tl.enabled:
            tl.record("recovery.fsm", state=s.name,
                      flat=self.flat_subtask, vertex=self.vertex_id,
                      subtask=self.subtask)

    # --- events (reference notify* methods) ---------------------------------

    def notify_start_recovery(self, in_edges: Sequence[int],
                              out_edges: Sequence[int]) -> None:
        if self.state != RecoveryState.STANDBY:
            raise RecoveryError(f"start_recovery in state {self.state}")
        self._pending_inputs = {e: False for e in in_edges}
        self._pending_outputs = {e: False for e in out_edges}
        self._goto(RecoveryState.WAITING_CONNECTIONS)
        if self._connections_ready():
            self._enter_waiting_determinants()

    def notify_state_restoration_complete(self) -> None:
        self._state_restored = True
        self._maybe_advance_connections()

    def notify_new_input_channel(self, edge: int) -> None:
        if edge in self._pending_inputs:
            self._pending_inputs[edge] = True
        self._maybe_advance_connections()

    def notify_new_output_channel(self, edge: int) -> None:
        if edge in self._pending_outputs:
            self._pending_outputs[edge] = True
        self._maybe_advance_connections()

    def _connections_ready(self) -> bool:
        # Advances only when every input AND output channel is established
        # and state restoration finished (WaitingConnectionsState.java:96).
        return (self._state_restored
                and all(self._pending_inputs.values())
                and all(self._pending_outputs.values()))

    def _maybe_advance_connections(self) -> None:
        if (self.state == RecoveryState.WAITING_CONNECTIONS
                and self._connections_ready()):
            self._enter_waiting_determinants()

    def _enter_waiting_determinants(self) -> None:
        self._goto(RecoveryState.WAITING_DETERMINANTS)

    def expect_determinant_responses(self, n: int) -> None:
        self._expected_responses = n
        self._expected_set = True
        self._maybe_have_determinants()

    def notify_determinant_response(self, rows: np.ndarray,
                                    abs_start: int) -> None:
        if self.state != RecoveryState.WAITING_DETERMINANTS:
            raise RecoveryError(f"determinant response in state {self.state}")
        self._responses.append((rows, abs_start))
        self._maybe_have_determinants()

    def _maybe_have_determinants(self) -> None:
        if (self.state == RecoveryState.WAITING_DETERMINANTS
                and self._expected_set
                and len(self._responses) >= self._expected_responses):
            self._goto(RecoveryState.REPLAYING)

    def merged_determinants(self) -> Tuple[np.ndarray, int]:
        from clonos_tpu.causal.replication import merge_determinant_responses
        return merge_determinant_responses(self._responses)

    def run_replay(self, plan: ReplayPlan,
                   defer_sync: bool = False) -> ReplayResult:
        if self.state != RecoveryState.REPLAYING:
            raise RecoveryError(f"replay in state {self.state}")
        self.plan = plan
        self.result = self.replayer.replay(plan, defer_sync=defer_sync)
        if plan.verify_outputs and not self.result.deferred:
            self.result.verify()
        self._goto(RecoveryState.RUNNING)
        return self.result
