"""Epoch tracking.

Capability of the reference's ``EpochTracker``/``EpochTrackerImpl``
(flink-runtime .../causal/EpochTrackerImpl.java:40 — incRecordCount:84,
startNewEpoch:94, setRecordCountTarget:111, fireAnyAsyncEvent:118), split
TPU-natively into:

- :class:`EpochState` — two int32 scalars carried *inside* the jitted step
  (epoch id, record count since epoch start), manipulated by pure functions
  so XLA sees straight-line arithmetic, no host chatter; and
- :class:`EpochTracker` — the host-side control-plane mirror that owns
  listener registration and async-determinant replay targets (targets only
  matter between supersteps, never inside the compiled hot loop).

Epoch n = all records between checkpoint barrier n and n+1; a completed
checkpoint truncates the causal and in-flight logs back to its boundary.
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Callable, List, NamedTuple, Optional, Tuple

import jax.numpy as jnp

from clonos_tpu.causal.determinant import Determinant


class EpochState(NamedTuple):
    """Device-resident epoch scalars (part of every task's step carry)."""

    epoch_id: jnp.ndarray      # int32 scalar
    record_count: jnp.ndarray  # int32 scalar, records since epoch start
    total_records: jnp.ndarray # int32 scalar, records since job start

    @staticmethod
    def initial(epoch_id: int = 0) -> "EpochState":
        z = jnp.asarray(0, jnp.int32)
        return EpochState(jnp.asarray(epoch_id, jnp.int32), z, z)


def inc_record_count(state: EpochState, n) -> EpochState:
    n = jnp.asarray(n, jnp.int32)
    return EpochState(state.epoch_id, state.record_count + n,
                      state.total_records + n)


def start_new_epoch(state: EpochState, new_epoch_id) -> EpochState:
    return EpochState(jnp.asarray(new_epoch_id, jnp.int32),
                      jnp.asarray(0, jnp.int32), state.total_records)


def total_records_near_wrap(state: EpochState,
                            margin: int = 1 << 29) -> jnp.ndarray:
    """True when the int32 job-lifetime record counter approaches 2^31; the
    control plane rebases it at a checkpoint fence (same int32-wrap
    discipline as log offsets, causal/log.py near_offset_wrap)."""
    return state.total_records > jnp.asarray((1 << 31) - 1 - margin,
                                             jnp.int32)


def rebase_total_records(state: EpochState, amount) -> EpochState:
    """Subtract a globally-agreed amount at a quiescent fence."""
    return state._replace(
        total_records=state.total_records - jnp.asarray(amount, jnp.int32))


@dataclasses.dataclass
class EpochTracker:
    """Host-side epoch control plane for one task.

    Maintains the listener bus and the async-determinant firing queue used
    during replay (reference fireAnyAsyncEvent:118: fire each stored async
    determinant exactly when record_count reaches its recorded target).
    """

    epoch_id: int = 0
    record_count: int = 0
    _epoch_listeners: List[Callable[[int], None]] = dataclasses.field(default_factory=list)
    _checkpoint_listeners: List[Callable[[int], None]] = dataclasses.field(default_factory=list)
    # (epoch_id, sealed digest) listeners — the audit plane's fan-out
    _seal_listeners: List[Callable[[int, object], None]] = dataclasses.field(default_factory=list)
    # sorted list of (epoch, target_record_count, seq, determinant, callback)
    _targets: List[Tuple[int, int, int, Determinant, Callable[[Determinant], None]]] = (
        dataclasses.field(default_factory=list))
    _seq: int = 0

    def subscribe_epoch_start(self, fn: Callable[[int], None]) -> None:
        self._epoch_listeners.append(fn)

    def subscribe_checkpoint_complete(self, fn: Callable[[int], None]) -> None:
        self._checkpoint_listeners.append(fn)

    def start_new_epoch(self, epoch_id: int) -> None:
        self.epoch_id = epoch_id
        self.record_count = 0
        # clonos: allow(join-discipline): listeners are registered during
        # wiring, before any worker thread exists (pre-start publication
        # across functions, which the race pass only models within the
        # spawning function); the list is never mutated after start.
        for fn in self._epoch_listeners:
            fn(epoch_id)
        # A replay target at record count 0 (first event of the new epoch)
        # must fire now (reference EpochTrackerImpl.startNewEpoch:94-103).
        self.fire_due_events()

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        for fn in self._checkpoint_listeners:
            fn(checkpoint_id)

    def subscribe_epoch_seal(self,
                             fn: Callable[[int, object], None]) -> None:
        """Audit plane: ``fn(epoch_id, digest)`` fires when an epoch's
        audit digest is sealed at its barrier (obs/audit.py) — BEFORE the
        checkpoint completes, so subscribers (wire shippers, tests) see
        the digest while the epoch's logs are still resident."""
        self._seal_listeners.append(fn)

    def notify_epoch_sealed(self, epoch_id: int, digest: object) -> None:
        # clonos: allow(join-discipline): seal listeners are registered
        # during wiring, before the fence worker starts (pre-start
        # publication across functions); never mutated after start.
        for fn in self._seal_listeners:
            fn(epoch_id, digest)

    def set_record_count_target(
        self, target: int, det: Determinant,
        callback: Callable[[Determinant], None],
        epoch: Optional[int] = None,
    ) -> None:
        """Register an async determinant to fire when ``record_count`` hits
        ``target`` within ``epoch`` (default: the current epoch) — replay
        path, reference setRecordCountTarget:111. A target in a *future*
        epoch may be pre-registered (e.g. record-count-0 events that fire
        the moment the next epoch starts, reference startNewEpoch:94-103);
        a target already passed within the current epoch is an error."""
        e = self.epoch_id if epoch is None else epoch
        if e < self.epoch_id or (e == self.epoch_id
                                 and target < self.record_count):
            raise ValueError(
                f"target epoch={e} count={target} already passed "
                f"(epoch={self.epoch_id}, record_count={self.record_count})")
        entry = (e, target, self._seq, det, callback)
        self._seq += 1
        # seq is unique, so tuple comparison never reaches the determinant.
        # clonos: allow(join-discipline): record-count targets register
        # and fire on the step thread only — inc_record_count is never
        # called from the fence tail (the reach chain the race pass
        # reports goes through cluster helpers the tail shares but does
        # not execute); replay installation runs with the tail joined.
        bisect.insort(self._targets, entry)
        # Fire immediately if already due (reference setRecordCountTarget:111
        # fires when recordCount == target at registration).
        self.fire_due_events()

    def inc_record_count(self, n: int = 1) -> None:
        self.record_count += n
        self.fire_due_events()

    def fire_due_events(self) -> None:
        while self._targets:
            e, target, _, det, callback = self._targets[0]
            due = e < self.epoch_id or (e == self.epoch_id
                                        and target <= self.record_count)
            if not due:
                return
            self._targets.pop(0)
            callback(det)

    @property
    def pending_targets(self) -> int:
        return len(self._targets)
