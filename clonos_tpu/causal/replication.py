"""Determinant replication: the piggyback channel, TPU-style.

The reference appends causal-log deltas to every outgoing netty
``BufferResponse`` and merges them on receive
(io/network/netty/NettyMessage.java:156-242, serde in
causal/log/job/serde/AbstractDeltaSerializerDeserializer.java:50, offset
dedup in ThreadCausalLogImpl.processUpstreamDelta:117, sharing-depth cut in
JobCausalLogImpl.respondToDeterminantRequest:192 and the serde's
insertNewUpstreamLog:165-193).

TPU-native re-design: replication is a **block-boundary collective**, not a
per-message payload. Every (owner subtask -> holder subtask) pair within the
sharing-depth cut is one row of a stacked replica log
``int32[R, capacity, lanes]``. The executor's block program appends the
same determinant tensor to owners and (owner-indexed) replicas in one fused
gather+scatter — replica heads therefore equal owner heads *by
construction* at every block fence, and the determinants describing a
block's outputs are on their holders before those outputs become externally
visible (the piggyback guarantee, NettyMessage.java:156-242). Under pjit
over a device mesh the owner-indexed gather lowers to the ICI all-gather
this design targets (SURVEY.md §2.6).

:func:`replicate_step` (pull + offset-dedup merge) remains the
*resynchronization* path — recovery catch-up and reconnect-after-gap —
mirroring the reference's processUpstreamDelta dedup semantics.

Transitive sharing: the reference relays a remote log's delta hop-by-hop;
here the sharing mask already contains every (owner, holder) pair within
depth (multi-hop distances via CausalGraphUtils-equivalent BFS), so delivery
is direct — same reachable-replica semantics, one hop.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.causal import log as clog
from clonos_tpu.graph.job_graph import JobGraph


@dataclasses.dataclass(frozen=True)
class ReplicationPlan:
    """Static description of who replicates whose log.

    ``pairs[r] = (owner_flat, holder_flat)`` over flat subtask indices
    (JobGraph.subtask_base layout).

    ``replication_factor`` bounds how many holder *subtasks* per
    (owner subtask, holder vertex) pair carry a replica: holder subtask
    ``(owner_sub + j) % P_holder`` for ``j < factor``. ``-1`` = every
    holder subtask (the reference's behavior, where every downstream TM
    within sharing depth accumulates the log via piggybacking —
    JobCausalLogImpl.java:71 keyed by CausalLogID with one copy per TM).
    A bounded factor is the memory-scalable default: the full bipartite
    product is O(V^2·P^2) log copies, structurally impossible at the
    128-task BASELINE configs; factor k survives any k-1 failures among
    an owner's chosen holders (plus arbitrary other failures), and k=P
    restores reference-equivalent redundancy.
    """

    pairs: Tuple[Tuple[int, int], ...]
    num_subtasks: int
    replication_factor: int = -1

    @classmethod
    def from_job(cls, job: JobGraph, sharing_depth: int = -1,
                 replication_factor: int = -1) -> "ReplicationPlan":
        info = job.graph_info(0)
        mask = info.sharing_mask(sharing_depth)
        pairs: List[Tuple[int, int]] = []
        for owner_v in range(len(job.vertices)):
            for holder_v in range(len(job.vertices)):
                if owner_v == holder_v or not mask[owner_v, holder_v]:
                    continue
                ob = job.subtask_base(owner_v)
                hb = job.subtask_base(holder_v)
                hp = job.vertices[holder_v].parallelism
                k = hp if replication_factor < 0 else min(replication_factor,
                                                          hp)
                for os_ in range(job.vertices[owner_v].parallelism):
                    for j in range(k):
                        pairs.append((ob + os_, hb + (os_ + j) % hp))
        return cls(tuple(pairs), job.total_subtasks(), replication_factor)

    @property
    def num_replicas(self) -> int:
        return len(self.pairs)

    def owner_index(self) -> jnp.ndarray:
        return jnp.asarray([o for o, _ in self.pairs], jnp.int32)

    def replicas_held_by(self, holder_flat: int) -> List[int]:
        """Replica row indices held by one subtask (its share of the stacked
        replica log — what it answers determinant requests from)."""
        return [r for r, (_, h) in enumerate(self.pairs) if h == holder_flat]

    def replicas_of(self, owner_flat: int) -> List[int]:
        return [r for r, (o, _) in enumerate(self.pairs) if o == owner_flat]


def create_replicas(plan: ReplicationPlan, capacity: int,
                    max_epochs: int) -> clog.ThreadLogState:
    """Stacked replica logs [R, capacity, lanes]."""
    return jax.vmap(lambda _: clog.create(capacity, max_epochs))(
        jnp.arange(max(plan.num_replicas, 1)))


def replicate_step(replicas: clog.ThreadLogState,
                   owner_logs: clog.ThreadLogState,
                   owner_idx: jnp.ndarray,
                   max_delta: int) -> Tuple[clog.ThreadLogState, jnp.ndarray]:
    """One replication round: pull each owner's fresh suffix into every
    replica. Pure function — runs inside the jitted superstep.

    Returns (replicas, lag) where ``lag[r]`` is how many rows replica r is
    still behind after this round (nonzero when the owner produced more than
    ``max_delta`` since last round; the next round catches up — determinant
    durability lags by that many rows, the analog of netty frames in
    flight)."""
    owners = jax.tree_util.tree_map(lambda x: x[owner_idx], owner_logs)
    buf, count, start = clog.v_slice_from(owners, replicas.head, max_delta)
    new_replicas, gaps = clog.v_merge_delta(replicas, buf, count, start)
    lag = owners.head - new_replicas.head
    return new_replicas, lag


def sync_replica_epochs(replicas: clog.ThreadLogState, epoch_id
                        ) -> clog.ThreadLogState:
    """Record the epoch index on replicas at the epoch fence. Run *after* a
    catch-up replication round so replica heads equal owner heads and the
    epoch->offset entries agree with the owners'."""
    return clog.v_start_epoch(replicas, epoch_id)


# --- recovery-side: determinant requests (host control plane) ---------------


def collect_determinant_response(
    replicas_host: clog.ThreadLogState, replica_rows: Sequence[int],
    from_epoch: int, max_out: int,
) -> Dict[int, Tuple[np.ndarray, int]]:
    """Serve a DeterminantRequest from this holder's replica rows
    (reference JobCausalLogImpl.respondToDeterminantRequest:188): for each
    replica row, all retained rows from ``from_epoch``'s start. Returns
    {replica_row: (rows ndarray, abs_start)}."""
    out: Dict[int, Tuple[np.ndarray, int]] = {}
    for r in replica_rows:
        one = jax.tree_util.tree_map(lambda x: x[r], replicas_host)
        buf, count, start = clog.get_determinants(one, from_epoch, max_out)
        out[r] = (np.asarray(buf)[: int(count)], int(start))
    return out


def merge_determinant_responses(
    responses: Sequence[Tuple[np.ndarray, int]],
) -> Tuple[np.ndarray, int]:
    """Merge responses from multiple holders (reference
    DeterminantResponseEvent.merge / AbstractState.java:106-143): every
    response is a prefix-consistent slice of the same owner log, so the
    merged view is the one reaching furthest, extended left to the earliest
    start. Verifies overlap consistency (bit-equality on shared offsets)."""
    if not responses:
        # Lane-shaped empty, not (0, 0): a zero-step replay (kill right
        # after a completed fence — the pipelined fence's joined tail
        # lands exactly there) still column-indexes the merged rows.
        from clonos_tpu.causal import determinant as det
        return np.zeros((0, det.NUM_LANES), np.int32), 0
    best_rows, best_start = None, 0
    for rows, start in responses:
        if best_rows is None:
            best_rows, best_start = rows.copy(), start
            continue
        # Consistency on the overlap:
        lo = max(start, best_start)
        hi = min(start + len(rows), best_start + len(best_rows))
        if hi > lo:
            a = best_rows[lo - best_start: hi - best_start]
            b = rows[lo - start: hi - start]
            if not np.array_equal(a, b):
                raise ValueError(
                    "divergent determinant responses: replicas disagree on "
                    f"offsets [{lo},{hi}) — protocol violation")
        # Extend right.
        if start + len(rows) > best_start + len(best_rows):
            tail_from = best_start + len(best_rows) - start
            if tail_from < 0:
                best_rows, best_start = rows.copy(), start
            else:
                best_rows = np.concatenate([best_rows, rows[tail_from:]])
        # Extend left.
        if start < best_start:
            head_upto = best_start - start
            best_rows = np.concatenate([rows[:head_upto], best_rows])
            best_start = start
    return best_rows, best_start
