"""Determinant schema: packed fixed-width tensor records.

Capability parity with the reference's determinant type family
(flink-runtime .../causal/determinant/Determinant.java:20-35 tag numbering;
payload classes OrderDeterminant.java:23, TimestampDeterminant.java:26,
RNGDeterminant.java:26, SerializableDeterminant.java,
TimerTriggerDeterminant.java:26, SourceCheckpointDeterminant.java:40-43,
IgnoreCheckpointDeterminant.java:32, BufferBuiltDeterminant.java:36, and the
AsyncDeterminant record-count contract) — but as a TPU-native layout instead
of a variable-width JVM byte codec:

    one determinant == one row of int32[NUM_LANES]

        lane 0: tag
        lane 1: record_count   (the AsyncDeterminant replay target; 0 for
                                synchronous determinants)
        lanes 2..7: payload    (64-bit values split hi/lo across two lanes)

A thread causal log is therefore a single ``int32[capacity, 8]`` ring buffer
in HBM; append is a dynamic-update-slice, delta extraction is a slice, replay
is a vectorized scan. The variable-width SERIALIZABLE payload does not fit a
fixed row, so its bytes live in a host-side *sidecar* blob store and the row
carries ``(sidecar_key, length, crc32)`` — rare/slow-path by design (it only
covers external-service calls, reference CausalSerializableServiceFactory).

The JVM encoder's GC-avoiding object pool (DeterminantPool.java) has no
analog here: rows are values, not objects.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import ClassVar, Dict, List, Optional, Sequence, Tuple, Type, Union

import numpy as np

# --- tags (numbering matches reference Determinant.java:20-35) --------------

ORDER = 0
TIMESTAMP = 1
RNG = 2
SERIALIZABLE = 3
TIMER_TRIGGER = 4
SOURCE_CHECKPOINT = 5
IGNORE_CHECKPOINT = 6
BUFFER_BUILT = 7
SCALE = 8

NUM_TAGS = 9
TAG_NAMES = (
    "ORDER", "TIMESTAMP", "RNG", "SERIALIZABLE", "TIMER_TRIGGER",
    "SOURCE_CHECKPOINT", "IGNORE_CHECKPOINT", "BUFFER_BUILT", "SCALE",
)

# Tags whose effect fires at a target record count during replay
# (reference AsyncDeterminant subclasses).
ASYNC_TAGS = frozenset({TIMER_TRIGGER, SOURCE_CHECKPOINT, IGNORE_CHECKPOINT})

# --- row layout -------------------------------------------------------------

NUM_LANES = 8
LANE_TAG = 0
LANE_RC = 1
LANE_P = 2  # first payload lane
ROW_DTYPE = np.int32
ROW_BYTES = NUM_LANES * 4

_I32_MASK = 0xFFFFFFFF


def split64(v: int) -> Tuple[int, int]:
    """Split a signed 64-bit int into (hi, lo) signed 32-bit lane values."""
    u = v & 0xFFFFFFFFFFFFFFFF
    hi, lo = (u >> 32) & _I32_MASK, u & _I32_MASK
    return _tosigned(hi), _tosigned(lo)


def join64(hi: int, lo: int) -> int:
    u = ((hi & _I32_MASK) << 32) | (lo & _I32_MASK)
    return u - (1 << 64) if u >= (1 << 63) else u


def _tosigned(u: int) -> int:
    return u - (1 << 32) if u >= (1 << 31) else u


# --- host-side determinant dataclasses --------------------------------------


@dataclasses.dataclass(frozen=True)
class Determinant:
    """Base: host-side view of one packed row."""

    TAG: ClassVar[int] = -1

    def pack(self) -> np.ndarray:
        row = np.zeros(NUM_LANES, dtype=ROW_DTYPE)
        row[LANE_TAG] = self.TAG
        row[LANE_RC] = getattr(self, "record_count", 0)
        payload = self._payload()
        for p in payload:
            # Single-lane values must fit 32 bits (signed range, or the
            # unsigned range for masked fields like crc32). Silent masking
            # here would corrupt the log and make replay diverge from the
            # original run undetected — fail loudly instead. 64-bit fields
            # (timestamps, checkpoint ids, sidecar keys) are split across
            # two lanes by their _payload() via split64.
            if not (-(1 << 31) <= p < (1 << 32)):
                raise ValueError(
                    f"{type(self).__name__} payload value {p} does not fit "
                    f"a 32-bit lane")
        row[LANE_P:LANE_P + len(payload)] = np.array(
            [_tosigned(p & _I32_MASK) for p in payload], dtype=np.int64
        ).astype(ROW_DTYPE)
        return row

    def _payload(self) -> Sequence[int]:
        return ()

    @classmethod
    def unpack(cls, row: np.ndarray) -> "Determinant":
        tag = int(row[LANE_TAG])
        sub = _TAG_TO_CLASS.get(tag)
        if sub is None:
            raise ValueError(f"unknown determinant tag {tag}")
        return sub._from_row(row)

    @classmethod
    def _from_row(cls, row: np.ndarray) -> "Determinant":
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class OrderDeterminant(Determinant):
    """Which input channel the next consumed batch came from.

    TPU-first note: the reference logs one ORDER determinant *per buffer*
    (CausalBufferOrderService.java:112). Here order is logged per consumed
    *batch* (one superstep input selection), which is the unit of
    nondeterministic interleaving in a batched dataflow.
    """

    TAG: ClassVar[int] = ORDER
    channel: int = 0

    def _payload(self):
        return (self.channel,)

    @classmethod
    def _from_row(cls, row):
        return cls(channel=int(row[LANE_P]))


@dataclasses.dataclass(frozen=True)
class TimestampDeterminant(Determinant):
    """A wall-clock read (reference CausalTimeService.currentTimeMillis)."""

    TAG: ClassVar[int] = TIMESTAMP
    timestamp: int = 0

    def _payload(self):
        return split64(self.timestamp)

    @classmethod
    def _from_row(cls, row):
        return cls(timestamp=join64(int(row[LANE_P]), int(row[LANE_P + 1])))


@dataclasses.dataclass(frozen=True)
class RNGDeterminant(Determinant):
    """A host-side random draw. (Device PRNG is already deterministic via
    counter-based keys; only host nondeterminism needs logging.)"""

    TAG: ClassVar[int] = RNG
    value: int = 0

    def _payload(self):
        return (self.value,)

    @classmethod
    def _from_row(cls, row):
        return cls(value=int(row[LANE_P]))


@dataclasses.dataclass(frozen=True)
class SerializableDeterminant(Determinant):
    """An arbitrary external-service result; bytes live in a sidecar store.
    The 64-bit sidecar key spans two lanes so long-running jobs never
    exhaust the key space."""

    TAG: ClassVar[int] = SERIALIZABLE
    sidecar_key: int = 0
    length: int = 0
    crc32: int = 0

    def _payload(self):
        khi, klo = split64(self.sidecar_key)
        return (khi, klo, self.length, self.crc32)

    @classmethod
    def _from_row(cls, row):
        return cls(sidecar_key=join64(int(row[LANE_P]), int(row[LANE_P + 1])),
                   length=int(row[LANE_P + 2]),
                   crc32=int(row[LANE_P + 3]) & _I32_MASK)


@dataclasses.dataclass(frozen=True)
class TimerTriggerDeterminant(Determinant):
    """A processing-time timer firing, replayed at record_count."""

    TAG: ClassVar[int] = TIMER_TRIGGER
    record_count: int = 0
    callback_id: int = 0
    timestamp: int = 0

    def _payload(self):
        hi, lo = split64(self.timestamp)
        return (self.callback_id, hi, lo)

    @classmethod
    def _from_row(cls, row):
        return cls(record_count=int(row[LANE_RC]),
                   callback_id=int(row[LANE_P]),
                   timestamp=join64(int(row[LANE_P + 1]), int(row[LANE_P + 2])))


@dataclasses.dataclass(frozen=True)
class SourceCheckpointDeterminant(Determinant):
    """A checkpoint-trigger RPC arrival at a source, replayed at record_count
    (reference SourceCheckpointDeterminant.java:40-43: recordCount, ckptID,
    ts, type, storageRef)."""

    TAG: ClassVar[int] = SOURCE_CHECKPOINT
    record_count: int = 0
    checkpoint_id: int = 0
    timestamp: int = 0
    checkpoint_type: int = 0
    storage_ref: int = 0

    def _payload(self):
        chi, clo = split64(self.checkpoint_id)
        thi, tlo = split64(self.timestamp)
        return (chi, clo, thi, tlo, self.checkpoint_type, self.storage_ref)

    @classmethod
    def _from_row(cls, row):
        p = [int(row[LANE_P + i]) for i in range(6)]
        return cls(record_count=int(row[LANE_RC]),
                   checkpoint_id=join64(p[0], p[1]),
                   timestamp=join64(p[2], p[3]),
                   checkpoint_type=p[4], storage_ref=p[5])


@dataclasses.dataclass(frozen=True)
class IgnoreCheckpointDeterminant(Determinant):
    """Skip a checkpoint the failed task never acked
    (reference IgnoreCheckpointDeterminant.java:32)."""

    TAG: ClassVar[int] = IGNORE_CHECKPOINT
    record_count: int = 0
    checkpoint_id: int = 0

    def _payload(self):
        return split64(self.checkpoint_id)

    @classmethod
    def _from_row(cls, row):
        return cls(record_count=int(row[LANE_RC]),
                   checkpoint_id=join64(int(row[LANE_P]), int(row[LANE_P + 1])))


@dataclasses.dataclass(frozen=True)
class BufferBuiltDeterminant(Determinant):
    """Output batch cut: exactly how many records went into an emitted batch
    (reference BufferBuiltDeterminant.java:36 logs numBytes per buffer cut;
    here the unit is records per emitted batch, which pins the batch boundary
    for bit-identical output reconstruction)."""

    TAG: ClassVar[int] = BUFFER_BUILT
    num_records: int = 0

    def _payload(self):
        return (self.num_records,)

    @classmethod
    def _from_row(cls, row):
        return cls(num_records=int(row[LANE_P]))


@dataclasses.dataclass(frozen=True)
class ScaleDeterminant(Determinant):
    """One autoscaling decision, logged before it acts.

    The paper's rule for nondeterministic control events (timer firings,
    checkpoint RPC arrivals) extends to autonomous scaling: the decision
    is recorded as a determinant so a recovered controller REPLAYS it
    instead of re-deciding — a re-decide against slightly different
    post-recovery signals would re-cut the cluster twice. ``record_count``
    carries the decision sequence number (nonzero, so a SCALE row can
    never masquerade as a per-step sync anchor); ``signal_crc`` pins the
    exact :class:`~clonos_tpu.autoscale.signals.ScaleSignals` snapshot the
    policy saw (full snapshot in the decision log's JSONL sidecar, same
    sidecar discipline as SERIALIZABLE). Not an ASYNC_TAG: SCALE rows live
    in the controller's own host-side log, never in a task's replayable
    determinant stream.
    """

    TAG: ClassVar[int] = SCALE
    record_count: int = 0      # decision sequence number (1-based)
    epoch: int = 0             # completed fence the decision was made at
    action: int = 0            # 0 hold / 1 scale-workers / 2 scale-replicas
    delta: int = 0             # signed step (bounded by policy max_step)
    target: int = 0            # resulting worker/replica count
    signal_crc: int = 0        # crc32 of the canonical signal snapshot

    def _payload(self):
        ehi, elo = split64(self.epoch)
        return (ehi, elo, self.action, self.delta, self.target,
                self.signal_crc)

    @classmethod
    def _from_row(cls, row):
        return cls(record_count=int(row[LANE_RC]),
                   epoch=join64(int(row[LANE_P]), int(row[LANE_P + 1])),
                   action=int(row[LANE_P + 2]),
                   delta=int(row[LANE_P + 3]),
                   target=int(row[LANE_P + 4]),
                   signal_crc=int(row[LANE_P + 5]) & _I32_MASK)


_TAG_TO_CLASS: Dict[int, Type[Determinant]] = {
    ORDER: OrderDeterminant,
    TIMESTAMP: TimestampDeterminant,
    RNG: RNGDeterminant,
    SERIALIZABLE: SerializableDeterminant,
    TIMER_TRIGGER: TimerTriggerDeterminant,
    SOURCE_CHECKPOINT: SourceCheckpointDeterminant,
    IGNORE_CHECKPOINT: IgnoreCheckpointDeterminant,
    BUFFER_BUILT: BufferBuiltDeterminant,
    SCALE: ScaleDeterminant,
}


# --- batch codec (reference SimpleDeterminantEncoder.java:35 equivalent) ----


def sync_anchors(rows: np.ndarray) -> np.ndarray:
    """Indices of per-step sync-block anchors in a packed row stream:
    TIMESTAMP rows with a ZERO record-count stamp. Async appends stamp a
    nonzero count precisely so they can't masquerade as step anchors
    (executor.append_async_determinant) — every consumer of the stream
    layout shares this one predicate."""
    rows = np.asarray(rows)
    return np.where((rows[:, LANE_TAG] == TIMESTAMP)
                    & (rows[:, LANE_RC] == 0))[0]


def pack_batch(dets: Sequence[Determinant]) -> np.ndarray:
    """Pack determinants into an ``int32[n, NUM_LANES]`` array."""
    if not dets:
        return np.zeros((0, NUM_LANES), dtype=ROW_DTYPE)
    return np.stack([d.pack() for d in dets])


def unpack_batch(rows: np.ndarray) -> List[Determinant]:
    return [Determinant.unpack(rows[i]) for i in range(rows.shape[0])]


def to_bytes(rows: np.ndarray) -> bytes:
    """Wire/spill serialization: contiguous little-endian rows."""
    return np.ascontiguousarray(rows.astype("<i4")).tobytes()


def from_bytes(data: bytes) -> np.ndarray:
    arr = np.frombuffer(data, dtype="<i4")
    if arr.size % NUM_LANES:
        raise ValueError(f"byte length {len(data)} is not a whole number of rows")
    return arr.reshape(-1, NUM_LANES).astype(ROW_DTYPE)


# --- sidecar store for SERIALIZABLE payloads --------------------------------


class SidecarStore:
    """Host-side blob store for variable-width SERIALIZABLE payloads.

    Epoch-scoped like the determinant log itself: blobs are tagged with the
    epoch they were created in and dropped when that epoch is truncated.

    Keys are namespaced by the owning task (``owner`` in the high bits) so
    blobs replicated between stores during recovery can never collide with
    locally-allocated keys. Keys are 64-bit (two log lanes): 2^40 blobs per
    owner over the job's lifetime — sequence numbers are never reused, so a
    key can never alias a stale replicated blob.
    """

    OWNER_SHIFT = 40

    def __init__(self, owner: int = 0):
        if not (0 <= owner < (1 << (63 - self.OWNER_SHIFT))):
            raise ValueError(f"owner id out of range: {owner}")
        self.owner = owner
        self._blobs: Dict[int, Tuple[int, bytes]] = {}
        self._next_seq = 1

    def put(self, data: bytes, epoch: int) -> SerializableDeterminant:
        if self._next_seq >= (1 << self.OWNER_SHIFT):
            raise RuntimeError("sidecar key space exhausted")
        key = (self.owner << self.OWNER_SHIFT) | self._next_seq
        self._next_seq += 1
        self._blobs[key] = (epoch, data)
        return SerializableDeterminant(
            sidecar_key=key, length=len(data), crc32=zlib.crc32(data))

    def get(self, det: SerializableDeterminant) -> bytes:
        epoch, data = self._blobs[det.sidecar_key]
        if len(data) != det.length or zlib.crc32(data) != det.crc32:
            raise ValueError(f"sidecar blob {det.sidecar_key} fails integrity check")
        return data

    def merge_from(self, other: "SidecarStore") -> None:
        """Adopt blobs replicated from another store (recovery path).

        Owner-namespaced keys make cross-store collisions impossible unless
        two stores share an owner id with divergent contents — that is a
        protocol violation and raises."""
        for key, (epoch, data) in other._blobs.items():
            existing = self._blobs.get(key)
            if existing is not None and existing[1] != data:
                raise ValueError(
                    f"sidecar key collision on {key}: divergent contents "
                    f"(duplicate owner id?)")
            self._blobs[key] = (epoch, data)

    def truncate(self, oldest_live_epoch: int) -> None:
        dead = [k for k, (e, _) in self._blobs.items() if e < oldest_live_epoch]
        for k in dead:
            del self._blobs[k]

    def __len__(self) -> int:
        return len(self._blobs)
