"""Causal services: intercepted nondeterminism for user/control code.

Capability parity with the reference's services API
(flink-core .../api/common/services/{TimeService,RandomService,
SerializableService,SerializableServiceFactory}.java, implementations in
flink-runtime .../causal/services/ — AbstractCausalService.java:40-73 with
the append-even-during-replay invariant :61-64, CausalTimeService.java:48-67,
PeriodicCausalTimeService, DeterministicCausalRandomService,
CausalSerializableServiceFactory; README example README.md:46-77).

TPU split of responsibilities:

- The *per-superstep* time/RNG values are step inputs logged by the
  executor itself (TIMESTAMP/RNG rows in the fixed per-step layout) — the
  PeriodicCausalTimeService model: one amortized read per superstep powers
  `ctx.time`/`ctx.rng_bits` inside compiled operators.
- These services cover *host-side* user/control code (sources pulling
  external data, sinks calling external systems, timers): each call logs an
  async determinant row into the owning task's device log **between**
  supersteps, via the executor's ``append_async_determinant`` hook. During
  replay the service serves recorded values back (and re-appends them, the
  reference's invariant, so the rebuilt log is bit-identical).
"""

from __future__ import annotations

import time as _time
from typing import Callable, List, Optional, Tuple

import numpy as np

from clonos_tpu.causal import determinant as det


class ReplayFeed:
    """Recorded async determinants for one task, served in order during
    replay (the service-side face of the LogReplayer)."""

    def __init__(self, dets: List[det.Determinant]):
        self._dets = list(dets)
        self._pos = 0

    def next_of(self, cls) -> det.Determinant:
        """Next recorded determinant, which must be of ``cls`` — recorded
        and replayed nondeterminism must line up one-to-one (reference
        LogReplayer.replayNext* contract)."""
        if self._pos >= len(self._dets):
            raise RuntimeError(
                f"replay feed exhausted: expected a {cls.__name__}")
        d = self._dets[self._pos]
        if not isinstance(d, cls):
            raise RuntimeError(
                f"replay feed mismatch: expected {cls.__name__}, recorded "
                f"{type(d).__name__} — nondeterministic call order diverged")
        self._pos += 1
        return d

    def exhausted(self) -> bool:
        return self._pos >= len(self._dets)

    @property
    def remaining(self) -> int:
        return len(self._dets) - self._pos


class AbstractCausalService:
    """Shared record/replay plumbing. ``append`` is the host->device-log
    hook (executor.append_async_determinant bound to one task); appends
    happen on the live path AND during replay (reference invariant
    AbstractCausalService.java:61-64) so the rebuilt log matches."""

    def __init__(self, append: Callable[[det.Determinant], None],
                 replay_feed: Optional[ReplayFeed] = None):
        self._append = append
        self._feed = replay_feed

    @property
    def recovering(self) -> bool:
        return self._feed is not None and not self._feed.exhausted()

    def _record_or_replay(self, cls, make: Callable[[], det.Determinant]
                          ) -> det.Determinant:
        if self.recovering:
            d = self._feed.next_of(cls)
        else:
            d = make()
        self._append(d)
        return d


class CausalTimeService(AbstractCausalService):
    """currentTimeMillis with record/replay (CausalTimeService.java:48)."""

    def __init__(self, append, replay_feed=None, clock=None):
        super().__init__(append, replay_feed)
        # clonos: allow(wallclock) — this IS the causal clock's source;
        # every read is logged as a TimestampDeterminant and replayed.
        self._clock = clock or (lambda: int(_time.time() * 1000))

    def current_time_millis(self) -> int:
        d = self._record_or_replay(
            det.TimestampDeterminant,
            lambda: det.TimestampDeterminant(timestamp=self._clock()))
        return d.timestamp


class PeriodicCausalTimeService(CausalTimeService):
    """Amortized time: the wall clock is sampled at most once per
    ``period_ms`` and reads in between return the cached value
    (reference PeriodicCausalTimeService.java — there a periodic task
    refreshes the field; here the refresh rides the read path, which is
    deterministic given the same record/replay stream). Every read
    still logs its TimestampDeterminant, so replay is exact even though
    the underlying clock was sampled sparsely."""

    def __init__(self, append, replay_feed=None, clock=None,
                 period_ms: int = 10):
        super().__init__(append, replay_feed, clock)
        self._period = period_ms
        self._raw_clock = self._clock
        self._cached = None
        self._next_refresh = float("-inf")

        def amortized() -> int:
            # Gate the (possibly expensive) time source behind the cheap
            # monotonic clock: it is sampled at most once per period_ms,
            # the actual amortization the periodic variant exists for.
            now = _time.monotonic() * 1000.0
            if self._cached is None or now >= self._next_refresh:
                self._cached = self._raw_clock()
                self._next_refresh = now + self._period
            return self._cached
        self._clock = amortized


class CausalRandomService(AbstractCausalService):
    """Host random draws with record/replay
    (DeterministicCausalRandomService equivalent)."""

    def __init__(self, append, replay_feed=None, seed: int = 0):
        super().__init__(append, replay_feed)
        self._rng = np.random.RandomState(seed)

    def next_int(self, bound: int = 1 << 31) -> int:
        d = self._record_or_replay(
            det.RNGDeterminant,
            lambda: det.RNGDeterminant(
                value=int(self._rng.randint(0, bound, dtype=np.int64))))
        return d.value


class CausalSerializableService(AbstractCausalService):
    """Wraps an arbitrary external call so its results replay
    (CausalSerializableServiceFactory; the README example's
    getSerializableServiceFactory entry point).

    ``fn`` maps request bytes -> response bytes. On the live path the
    response is stored in the sidecar store and its (key, len, crc) row
    logged; during replay the recorded response is fetched instead of
    calling ``fn`` (external systems are NOT re-invoked — exactly-once)."""

    def __init__(self, append, fn: Callable[[bytes], bytes],
                 sidecar: det.SidecarStore, epoch_of: Callable[[], int],
                 replay_feed: Optional[ReplayFeed] = None):
        super().__init__(append, replay_feed)
        self._fn = fn
        self._sidecar = sidecar
        self._epoch_of = epoch_of

    def apply(self, request: bytes) -> bytes:
        if self.recovering:
            d = self._feed.next_of(det.SerializableDeterminant)
            self._append(d)
            return self._sidecar.get(d)
        response = self._fn(request)
        d = self._sidecar.put(response, self._epoch_of())
        self._append(d)
        return response


class CausalServiceFactory:
    """Per-task bundle (what the reference exposes through
    StreamingRuntimeContext / ManagedInitializationContext)."""

    def __init__(self, append, sidecar: det.SidecarStore,
                 epoch_of: Callable[[], int],
                 replay_feed: Optional[ReplayFeed] = None,
                 seed: int = 0, clock=None):
        self._append = append
        self._sidecar = sidecar
        self._epoch_of = epoch_of
        self._feed = replay_feed
        self._seed = seed
        self._clock = clock

    def time_service(self) -> CausalTimeService:
        return CausalTimeService(self._append, self._feed, self._clock)

    def periodic_time_service(self, period_ms: int = 10
                              ) -> "PeriodicCausalTimeService":
        return PeriodicCausalTimeService(self._append, self._feed,
                                         self._clock, period_ms)

    def random_service(self) -> CausalRandomService:
        return CausalRandomService(self._append, self._feed, self._seed)

    def serializable_service(self, fn: Callable[[bytes], bytes]
                             ) -> CausalSerializableService:
        return CausalSerializableService(self._append, fn, self._sidecar,
                                         self._epoch_of, self._feed)
