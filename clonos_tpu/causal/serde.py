"""Determinant-delta wire format: FLAT and GROUPED encodings.

Reference: causal/log/job/serde/ — AbstractDeltaSerializerDeserializer
.java:50 frames `[delta header][delta payloads]` onto outgoing buffers
(header = epoch + per-thread-log {id, offsetFromEpoch, deltaSize});
FlatDeltaSerializerDeserializer writes one full CausalLogID per entry,
GroupingDeltaSerializerDeserializer shares the vertex/partition prefix
across consecutive entries (hierarchy/VertexCausalLogs.java).

TPU build: intra-chip replication needs no bytes at all (the block
program bulk-appends owner rows into replicas directly), so this codec is
the CROSS-HOST path: a host serializes its device logs' fresh suffixes
into one frame, ships it over the control/data transport
(parallel/transport.py), and the receiving host merges the rows into its
replica logs with the same offset-dedup rule as on-chip
(log.merge_delta). Layout (little-endian):

    frame   = MAGIC u32 | encoding u8 | count u32 | entry*
    FLAT    entry = log_id i32 | abs_start i32 | n_rows u32 | rows
    GROUPED entry = vertex i16 | n_subs u16 |
                    (subtask i16 | abs_start i32 | n_rows u32 | rows)*
    rows    = n_rows * NUM_LANES * i32, followed by crc32 u32 of rows

The CRC and the bulk row memcpy are the per-frame hot path; a C++
implementation (native/delta_codec.cpp, loaded via ctypes) handles them
when built, with a bit-identical pure-Python fallback
(tests/test_remote.py::test_native_codec_matches_python_fallback pins
parity).
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Sequence, Tuple

import numpy as np

from clonos_tpu.causal import determinant as det

MAGIC = 0xC10_905
FLAT = 0
GROUPED = 1

_HDR = struct.Struct("<IBI")
_FLAT_E = struct.Struct("<iiI")
_GRP_V = struct.Struct("<hH")
_GRP_S = struct.Struct("<hiI")
_CRC = struct.Struct("<I")


def _crc(rows: np.ndarray) -> int:
    from clonos_tpu.ops import native
    return native.crc32(np.ascontiguousarray(rows, dtype=np.int32))


#: one log's delta: (flat log id, absolute start offset, rows [n, lanes])
Delta = Tuple[int, int, np.ndarray]


def encode_delta(deltas: Sequence[Delta], encoding: str = "flat",
                 subtasks_per_vertex: int = 1) -> bytes:
    """Serialize per-log fresh suffixes into one wire frame."""
    if encoding == "flat":
        enc = FLAT
    elif encoding == "grouped":
        enc = GROUPED
    else:
        raise ValueError(f"unknown delta encoding {encoding!r} "
                         f"(expected 'flat' or 'grouped')")
    out = [_HDR.pack(MAGIC, enc, len(deltas))]
    if enc == FLAT:
        from clonos_tpu.ops import native
        if native.available() and deltas:
            # One native pass over all entries (C ABI, native/delta_codec
            # .cpp): framing + CRC without per-entry Python overhead.
            rows_list = [np.ascontiguousarray(r, np.int32)
                         for _, _, r in deltas]
            body = native.encode_flat_entries(
                np.asarray([d[0] for d in deltas], np.int32),
                np.asarray([d[1] for d in deltas], np.int32),
                np.asarray([r.shape[0] for r in rows_list], np.uint32),
                (np.concatenate([r.reshape(-1) for r in rows_list])
                 if rows_list else np.zeros((0,), np.int32)),
                det.NUM_LANES)
            out.append(body)
            return b"".join(out)
        for log_id, start, rows in deltas:
            rows = np.ascontiguousarray(rows, dtype=np.int32)
            out.append(_FLAT_E.pack(log_id, start, rows.shape[0]))
            out.append(rows.tobytes())
            out.append(_CRC.pack(_crc(rows)))
    else:
        # Group consecutive logs by vertex: the vertex id is written once
        # per group (the reference's hierarchy savings).
        groups: Dict[int, List[Tuple[int, int, np.ndarray]]] = {}
        for log_id, start, rows in deltas:
            v, s = divmod(log_id, subtasks_per_vertex)
            groups.setdefault(v, []).append((s, start, rows))
        out = [_HDR.pack(MAGIC, enc, len(groups))]
        for v in sorted(groups):
            subs = groups[v]
            out.append(_GRP_V.pack(v, len(subs)))
            for s, start, rows in subs:
                rows = np.ascontiguousarray(rows, dtype=np.int32)
                out.append(_GRP_S.pack(s, start, rows.shape[0]))
                out.append(rows.tobytes())
                out.append(_CRC.pack(_crc(rows)))
    return b"".join(out)


def decode_delta(frame: bytes, subtasks_per_vertex: int = 1
                 ) -> List[Delta]:
    """Parse a wire frame back into (log_id, abs_start, rows) deltas,
    verifying each rows block's CRC."""
    magic, enc, count = _HDR.unpack_from(frame, 0)
    if magic != MAGIC:
        raise ValueError(f"bad delta frame magic {magic:#x}")
    pos = _HDR.size
    deltas: List[Delta] = []

    def read_rows(n: int, at: int) -> Tuple[np.ndarray, int]:
        nbytes = n * det.NUM_LANES * 4
        rows = np.frombuffer(frame, np.int32, n * det.NUM_LANES,
                             at).reshape(n, det.NUM_LANES)
        (crc,) = _CRC.unpack_from(frame, at + nbytes)
        if crc != _crc(rows):
            raise ValueError("delta rows CRC mismatch (corrupt frame)")
        return rows, at + nbytes + _CRC.size

    if enc == FLAT:
        for _ in range(count):
            log_id, start, n = _FLAT_E.unpack_from(frame, pos)
            pos += _FLAT_E.size
            rows, pos = read_rows(n, pos)
            deltas.append((log_id, start, rows))
    elif enc == GROUPED:
        for _ in range(count):
            v, n_subs = _GRP_V.unpack_from(frame, pos)
            pos += _GRP_V.size
            for _ in range(n_subs):
                s, start, n = _GRP_S.unpack_from(frame, pos)
                pos += _GRP_S.size
                rows, pos = read_rows(n, pos)
                deltas.append((v * subtasks_per_vertex + s, start, rows))
    else:
        raise ValueError(f"unknown delta encoding {enc}")
    return deltas


# --- lineage tag piggyback ---------------------------------------------------
# obs/lineage.py dyes k records per epoch by key hash; when exchanges
# leave the process, the dyed records' compact tags ride ordinary data
# frames next to the determinant deltas above (the ROADMAP multi-host
# item's "piggybacked on ordinary data messages", paid only for the k
# dyed records — a disabled plane ships zero tag bytes). One tag is
# five i64 lanes:
#
#     tag   = src_offset i64 | epoch i64 | step i64 | worker i64 |
#             vertex i64
#     frame = MAGIC u32 | encoding(=2) u8 | count u32 | tags | crc32 u32

LINEAGE = 2

#: one dyed record's tag: (src_offset, epoch, step, worker, vertex)
LineageTag = Tuple[int, int, int, int, int]

_TAG_LANES = 5


def encode_lineage_tags(tags: Sequence[LineageTag]) -> bytes:
    """Frame dyed-record lineage tags for the cross-host data path."""
    arr = np.asarray(list(tags), np.int64).reshape(-1, _TAG_LANES)
    payload = np.ascontiguousarray(arr).tobytes()
    return (_HDR.pack(MAGIC, LINEAGE, arr.shape[0]) + payload
            + _CRC.pack(zlib.crc32(payload)))


def decode_lineage_tags(frame: bytes) -> List[LineageTag]:
    """Decode a lineage-tag frame (CRC-checked, like delta rows)."""
    magic, enc, count = _HDR.unpack_from(frame, 0)
    if magic != MAGIC:
        raise ValueError(f"bad lineage frame magic {magic:#x}")
    if enc != LINEAGE:
        raise ValueError(f"not a lineage frame (encoding {enc})")
    nbytes = count * _TAG_LANES * 8
    arr = np.frombuffer(frame, np.int64, count * _TAG_LANES,
                        _HDR.size).reshape(count, _TAG_LANES)
    (crc,) = _CRC.unpack_from(frame, _HDR.size + nbytes)
    if crc != zlib.crc32(frame[_HDR.size:_HDR.size + nbytes]):
        raise ValueError("lineage tag CRC mismatch (corrupt frame)")
    return [tuple(int(x) for x in row) for row in arr]
