"""Thread causal log: device-resident determinant ring buffer.

Capability parity with the reference's ``ThreadCausalLogImpl``
(flink-runtime .../causal/log/thread/ThreadCausalLogImpl.java:51 —
appendDeterminant:158, processUpstreamDelta:117 (dedup overlapping deltas by
offset), hasDeltaForConsumer:196, getDeltaForConsumer:249,
getDeterminants:285, makeDeltaUnsafe:364, notifyCheckpointComplete:398
(truncation as offset rebase, no copy)) — re-designed for TPU:

- The log is one ``int32[capacity, NUM_LANES]`` ring buffer in HBM plus a
  handful of int32 scalars, bundled as the :class:`ThreadLogState` pytree.
- All offsets are *absolute* (monotonic append counts); ring position is
  ``offset % capacity``. Truncation advances ``tail`` — no copying, exactly
  the reference's index-rebase trick but free because offsets never move.
- Every operation is a pure function on the state, so XLA fuses appends into
  the surrounding step and ``jax.vmap`` batches the same operation over all
  logs on a device (the stacked-log layout — see :func:`stack_logs`).
- The JVM version guards epochs with read/write locks
  (ThreadCausalLogImpl.java:63-70); here there is nothing to lock — appends
  are data dependencies in a single traced program, ordered by XLA.

Static-shape discipline: appends take a fixed-size padded row buffer plus a
count; delta extraction returns a fixed-size buffer plus a count. Capacity
must be a power of two (cheap masking instead of modulo).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.causal.determinant import NUM_LANES


class ThreadLogState(NamedTuple):
    """Pytree state of one thread causal log (all device-resident)."""

    rows: jnp.ndarray          # int32[capacity, NUM_LANES] ring storage
    head: jnp.ndarray          # int32 scalar: absolute append count
    tail: jnp.ndarray          # int32 scalar: absolute oldest retained offset
    epoch_starts: jnp.ndarray  # int32[max_epochs]: absolute start offset of
                               #   epoch e at index e % max_epochs
    epoch_base: jnp.ndarray    # int32 scalar: oldest retained epoch id
    latest_epoch: jnp.ndarray  # int32 scalar: newest epoch recorded via
                               #   start_epoch (for epoch-index overflow
                               #   detection)

    @property
    def capacity(self) -> int:
        return self.rows.shape[0]

    @property
    def max_epochs(self) -> int:
        return self.epoch_starts.shape[0]


def create(capacity: int, max_epochs: int) -> ThreadLogState:
    if capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two, got {capacity}")
    z = jnp.asarray(0, jnp.int32)
    return ThreadLogState(
        rows=jnp.zeros((capacity, NUM_LANES), jnp.int32),
        head=z, tail=z,
        epoch_starts=jnp.zeros((max_epochs,), jnp.int32),
        epoch_base=z, latest_epoch=z,
    )


def size(state: ThreadLogState) -> jnp.ndarray:
    """Live determinants currently retained."""
    return state.head - state.tail


def overflowed(state: ThreadLogState) -> jnp.ndarray:
    """True if appends have clobbered un-truncated determinants (the JVM
    analog is the determinant BufferPool running dry)."""
    return size(state) > state.capacity


def epoch_index_overflowed(state: ThreadLogState) -> jnp.ndarray:
    """True if more than ``max_epochs`` epochs are un-truncated, i.e.
    ``start_epoch`` has overwritten a live epoch's index slot and a later
    ``truncate`` could advance ``tail`` past retained determinants. The
    control plane must check this (and stall epoch rolls / force a
    checkpoint) before it bites."""
    return state.latest_epoch - state.epoch_base + 1 > state.max_epochs


def near_offset_wrap(state: ThreadLogState, margin: int = 1 << 29) -> jnp.ndarray:
    """True when absolute int32 offsets approach 2^31 and the control plane
    should trigger a coordinated :func:`rebase` at the next checkpoint."""
    return state.head > jnp.asarray((1 << 31) - 1 - margin, jnp.int32)


def rebase(state: ThreadLogState, amount) -> ThreadLogState:
    """Subtract ``amount`` from every absolute offset (head/tail/epoch
    index). Safe only when all producers and replicas of this log rebase by
    the same globally-agreed amount (a multiple of capacity, so ring
    positions are unchanged) at a quiescent point — the checkpoint fence.
    This is the int32-wrap mitigation for long-running streams."""
    amount = jnp.asarray(amount, jnp.int32)
    return state._replace(
        head=state.head - amount,
        tail=state.tail - amount,
        epoch_starts=state.epoch_starts - amount,
    )


def append(state: ThreadLogState, rows: jnp.ndarray, count) -> ThreadLogState:
    """Append the first ``count`` rows of a padded ``[max_batch, NUM_LANES]``
    buffer at head (reference appendDeterminant:158, vectorized)."""
    max_batch = rows.shape[0]
    count = jnp.asarray(count, jnp.int32)
    idx = jnp.arange(max_batch, dtype=jnp.int32)
    pos = (state.head + idx) & (state.capacity - 1)
    live = idx < count
    # Masked scatter: positions past `count` write back their current value.
    current = state.rows[pos]
    vals = jnp.where(live[:, None], rows, current)
    new_rows = state.rows.at[pos].set(vals, mode="drop")
    return state._replace(rows=new_rows, head=state.head + count)


def append_full(state: ThreadLogState, rows: jnp.ndarray) -> ThreadLogState:
    """Append ALL rows of ``[n, NUM_LANES]`` at head — the block-fence bulk
    path (n is static and <= capacity, so ring positions are unique).

    Large appends use a DENSE formulation — pad the chunk to capacity,
    roll it into ring position, select — because the TPU executes a
    general row scatter ~row-at-a-time (~0.1us/row: the replica bulk
    append was the single hottest op of the whole live block program,
    tools/ab_append A/B: 171ms -> 47ms at [384, 65536] x 4096 rows).
    Small appends keep the scatter (the dense form's cost is O(capacity)
    regardless of n)."""
    n = rows.shape[0]
    cap = state.capacity
    if n > cap:
        raise ValueError(f"bulk append of {n} rows > capacity {cap}")
    if n * 64 >= cap:
        w = 1 << (2 * n - 1).bit_length()     # pow2 window >= 2n
        if 4 * w <= cap:
            # Windowed RMW: the chunk spans at most two W-aligned ring
            # windows; roll it within a [2W] strip and read-merge-write
            # those two windows at their (traced, aligned) starts. Work
            # is O(W) = O(n) per append — the whole-capacity
            # pad/roll/select below costs O(capacity), which doubled the
            # live append bill when log capacities grew to 1<<17.
            o = state.head & (cap - 1)
            r = o & (w - 1)
            base = o - r                        # W-aligned, traced
            strip = jnp.pad(rows, ((0, 2 * w - n), (0, 0)))
            strip = jnp.roll(strip, r, axis=0)
            idx2 = jnp.arange(2 * w, dtype=jnp.int32)
            mask = (idx2 >= r) & (idx2 < r + n)
            out = state.rows
            for half in (0, 1):
                start = (base + half * w) & (cap - 1)
                seg = jax.lax.dynamic_slice_in_dim(strip, half * w, w)
                m = jax.lax.dynamic_slice_in_dim(mask, half * w, w)
                win = jax.lax.dynamic_slice(
                    out, (start, jnp.zeros((), jnp.int32)),
                    (w, NUM_LANES))
                merged = jnp.where(m[:, None], seg, win)
                out = jax.lax.dynamic_update_slice(
                    out, merged, (start, jnp.zeros((), jnp.int32)))
            return state._replace(rows=out, head=state.head + n)
        o = state.head & (cap - 1)
        padded = jnp.pad(rows, ((0, cap - n), (0, 0)))
        rolled = jnp.roll(padded, o, axis=0)
        idx = jnp.arange(cap, dtype=jnp.int32)
        in_win = ((idx - o) & (cap - 1)) < n
        return state._replace(
            rows=jnp.where(in_win[:, None], rolled, state.rows),
            head=state.head + n)
    pos = (state.head + jnp.arange(n, dtype=jnp.int32)) & (cap - 1)
    return state._replace(rows=state.rows.at[pos].set(rows,
                                                      unique_indices=True),
                          head=state.head + n)


def append_one(state: ThreadLogState, row: jnp.ndarray) -> ThreadLogState:
    """Append a single row (hot path inside a traced step)."""
    pos = state.head & (state.capacity - 1)
    return state._replace(rows=state.rows.at[pos].set(row),
                          head=state.head + 1)


def start_epoch(state: ThreadLogState, epoch_id) -> ThreadLogState:
    """Record the epoch -> offset index entry for a newly started epoch.

    If more than ``max_epochs`` epochs pile up un-truncated this overwrites
    the oldest live slot — detectable via :func:`epoch_index_overflowed`,
    which the checkpoint coordinator checks each epoch roll."""
    e = jnp.asarray(epoch_id, jnp.int32)
    slot = e % state.max_epochs
    return state._replace(
        epoch_starts=state.epoch_starts.at[slot].set(state.head),
        latest_epoch=jnp.maximum(state.latest_epoch, e))


def epoch_start_offset(state: ThreadLogState, epoch_id) -> jnp.ndarray:
    e = jnp.asarray(epoch_id, jnp.int32)
    return state.epoch_starts[e % state.max_epochs]


def truncate(state: ThreadLogState, completed_epoch) -> ThreadLogState:
    """Checkpoint ``completed_epoch`` finished: drop determinants of epochs
    <= completed_epoch (reference notifyCheckpointComplete:398). Pure offset
    rebase; storage is untouched."""
    e = jnp.asarray(completed_epoch, jnp.int32)
    new_tail = epoch_start_offset(state, e + 1)
    # Never move backwards (late / duplicate notifications are no-ops).
    new_tail = jnp.maximum(new_tail, state.tail)
    new_base = jnp.maximum(e + 1, state.epoch_base)
    return state._replace(tail=new_tail, epoch_base=new_base)


def slice_from(
    state: ThreadLogState, abs_offset, max_out: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Gather rows [abs_offset, head) into a fixed-size buffer.

    Returns ``(buf[max_out, NUM_LANES], count, start_offset)`` — the delta
    triple that is this framework's wire format (reference makeDeltaUnsafe:364
    zero-copy slice; here a gather that XLA fuses into the consumer).
    """
    start = jnp.maximum(jnp.asarray(abs_offset, jnp.int32), state.tail)
    count = jnp.clip(state.head - start, 0, max_out)
    idx = jnp.arange(max_out, dtype=jnp.int32)
    pos = (start + idx) & (state.capacity - 1)
    buf = jnp.where((idx < count)[:, None], state.rows[pos], 0)
    return buf, count, start


def get_determinants(state: ThreadLogState, from_epoch, max_out: int):
    """All retained determinants from the start of ``from_epoch``
    (reference getDeterminants:285 — the replay feed)."""
    return slice_from(state, epoch_start_offset(state, from_epoch), max_out)


def merge_delta(
    state: ThreadLogState, rows: jnp.ndarray, count, abs_start
) -> Tuple[ThreadLogState, jnp.ndarray]:
    """Ingest a replicated delta of another task's log into this replica.

    Dedups by absolute offset exactly like the reference's
    ``processUpstreamDelta:117``: entries with offset < head are already
    present and skipped; only the fresh suffix is appended.

    Returns ``(new_state, gap)``. ``gap`` is True when ``abs_start > head``
    (a preceding delta was lost, e.g. across a reconnect): nothing is
    appended — absorbing the delta would record rows under wrong offsets —
    and the caller must request a full re-send from ``head``.
    """
    max_batch = rows.shape[0]
    count = jnp.asarray(count, jnp.int32)
    abs_start = jnp.asarray(abs_start, jnp.int32)
    gap = abs_start > state.head
    skip = jnp.clip(state.head - abs_start, 0, count)
    fresh = jnp.where(gap, 0, count - skip)
    idx = jnp.arange(max_batch, dtype=jnp.int32)
    shifted = jnp.where(idx + skip < max_batch, idx + skip, 0)
    fresh_rows = rows[shifted]
    return append(state, fresh_rows, fresh), gap


def sync_epoch_index(state: ThreadLogState, epoch_id) -> ThreadLogState:
    """Replica-side epoch bookkeeping: note that ``epoch_id`` starts at the
    replica's current head (called when the owner signals an epoch roll)."""
    return start_epoch(state, epoch_id)


# --- stacked-log layout -----------------------------------------------------
#
# A device holds many thread logs (its own main-thread + per-subpartition
# logs, plus replicas of upstream logs within sharing depth). Stacking them
# as one [L, capacity, NUM_LANES] pytree and vmapping the ops turns "for each
# log: append/merge/slice" into single fused XLA ops — the TPU answer to the
# reference's per-log object graph (JobCausalLogImpl's flat + hierarchical
# maps).

v_append = jax.vmap(append)
v_append_full = jax.vmap(append_full)
v_merge_delta = jax.vmap(merge_delta)
v_slice_from = jax.vmap(slice_from, in_axes=(0, 0, None))
v_truncate = jax.vmap(truncate, in_axes=(0, None))
v_start_epoch = jax.vmap(start_epoch, in_axes=(0, None))


def stack_logs(states) -> ThreadLogState:
    """Stack per-log states into one vmappable stacked state."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


def unstack_logs(stacked: ThreadLogState):
    n = stacked.head.shape[0]
    return [jax.tree_util.tree_map(lambda x: x[i], stacked) for i in range(n)]


def epoch_row_windows(stacked: ThreadLogState, epoch_slot,
                      max_rows: int) -> Tuple[jnp.ndarray, jnp.ndarray,
                                              jnp.ndarray]:
    """Gather one sealed epoch's determinant window from every stacked log
    in a single fused device op — the extraction half of tiered spilling
    (storage/tiered.py): called at the seal point, *before* the roll stamps
    the next epoch's start, so each log's window is ``[start, head)``.

    Returns ``(rows, counts, starts)`` where ``rows`` is
    ``int32[L, max_rows, NUM_LANES]`` (rows past a log's count are
    ring-garbage padding and must be trimmed by the caller), ``counts`` is
    ``int32[L]`` live rows per log, and ``starts`` is ``int32[L]`` absolute
    start offsets. ``max_rows`` is a static bound; the caller checks
    ``counts.max() <= max_rows`` and falls back to an exact host-side
    extraction on overflow (a mis-sized bound must degrade, not truncate).
    """
    epoch_slot = jnp.asarray(epoch_slot, jnp.int32)
    cap = stacked.rows.shape[1]
    starts = jnp.take(stacked.epoch_starts, epoch_slot, axis=1)     # [L]
    idx = starts[:, None] + jnp.arange(max_rows, dtype=jnp.int32)[None, :]
    pos = idx & (cap - 1)                                           # [L, W]
    rows = jnp.take_along_axis(stacked.rows, pos[:, :, None], axis=1)
    counts = stacked.head - starts
    return rows, counts, starts


# --- host-side convenience wrapper (tests / control plane) ------------------


class ThreadCausalLog:
    """Thin OO wrapper over the functional core, for host-side use.

    The executor never uses this in the hot path — there, log states live in
    the jitted step carry. This wrapper backs unit tests and the recovery
    control plane's host-side log manipulation.
    """

    # Jitted wrappers are class-level so every instance shares one trace/
    # compile cache (dozens of host-side wrappers exist per device).
    _append1 = staticmethod(jax.jit(append_one))
    _append = staticmethod(jax.jit(append))
    _truncate = staticmethod(jax.jit(truncate))
    _start_epoch = staticmethod(jax.jit(start_epoch))
    _merge = staticmethod(jax.jit(merge_delta))

    def __init__(self, capacity: int = 1 << 12, max_epochs: int = 64):
        self.state = create(capacity, max_epochs)

    def append_rows(self, rows: np.ndarray) -> None:
        if rows.ndim != 2 or rows.shape[1] != NUM_LANES:
            raise ValueError(f"expected [n, {NUM_LANES}] rows, got {rows.shape}")
        self.state = self._append(self.state, jnp.asarray(rows, jnp.int32),
                                  rows.shape[0])

    def start_epoch(self, epoch_id: int) -> None:
        self.state = self._start_epoch(self.state, epoch_id)

    def notify_checkpoint_complete(self, epoch_id: int) -> None:
        self.state = self._truncate(self.state, epoch_id)

    def merge_delta(self, rows: np.ndarray, abs_start: int) -> bool:
        """Returns True on success; False when a gap was detected (nothing
        merged — request a full re-send from ``self.head``)."""
        self.state, gap = self._merge(self.state, jnp.asarray(rows, jnp.int32),
                                      rows.shape[0], abs_start)
        return not bool(gap)

    def delta_for_consumer(self, consumer_offset: int, max_out: int):
        buf, count, start = slice_from(self.state, consumer_offset, max_out)
        return np.asarray(buf)[: int(count)], int(start)

    def determinants_from_epoch(self, epoch: int, max_out: int) -> np.ndarray:
        buf, count, _ = get_determinants(self.state, epoch, max_out)
        return np.asarray(buf)[: int(count)]

    @property
    def capacity(self) -> int:
        return self.state.capacity

    @property
    def head(self) -> int:
        return int(self.state.head)

    @property
    def tail(self) -> int:
        return int(self.state.tail)

    def __len__(self) -> int:
        return int(size(self.state))
