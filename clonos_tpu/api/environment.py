"""User-facing job builder: the StreamExecutionEnvironment analog.

Capability parity with the reference's fluent DataStream API
(flink-streaming-java .../environment/StreamExecutionEnvironment.java:105,
datastream/DataStream.java & KeyedStream) pared to the batched-TPU operator
set. The builder accumulates vertices/edges into a :class:`JobGraph`;
``execute`` hands it to the runtime executor.

Example (the SocketWindowWordCount shape, README.md:46-77 of the reference):

    env = StreamEnvironment(num_key_groups=128)
    (env.source(SyntheticSource(vocab=1000, batch_size=64), parallelism=4)
        .key_by()
        .window_count(num_keys=1000, window_size=5)
        .sink())
    job = env.build()
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from clonos_tpu.api.operators import (
    FilterOperator, HostFeedSource, IntervalJoinOperator, KeyedReduceOperator,
    MapOperator, Operator, SinkOperator, SyntheticSource,
    TumblingWindowCountOperator, UnionOperator,
)
from clonos_tpu.graph.job_graph import JobGraph, JobVertex, PartitionType


class DataStream:
    """Handle to a vertex's output; transformation methods append vertices."""

    def __init__(self, env: "StreamEnvironment", vertex: JobVertex,
                 keyed: bool = False):
        self._env = env
        self._vertex = vertex
        self._keyed = keyed

    # --- exchange selection --------------------------------------------------

    def key_by(self) -> "DataStream":
        """Marks the next operator's input as HASH-partitioned by key
        (KeyedStream analog). Keys are the record ``keys`` lane."""
        return DataStream(self._env, self._vertex, keyed=True)

    def _attach(self, name: str, op: Operator, parallelism: Optional[int],
                partition: Optional[PartitionType] = None,
                capacity: Optional[int] = None) -> "DataStream":
        p = parallelism or self._vertex.parallelism
        v = self._env.graph.add_vertex(name, op, p)
        if partition is None:
            if self._keyed:
                partition = PartitionType.HASH
            elif getattr(self, "_force_rebalance", False):
                partition = PartitionType.REBALANCE
            elif p == self._vertex.parallelism:
                partition = PartitionType.FORWARD
            else:
                partition = PartitionType.REBALANCE
        cap = capacity or self._env.default_edge_capacity
        self._env.graph.add_edge(self._vertex, v, partition, cap)
        return DataStream(self._env, v)

    # --- transformations -----------------------------------------------------

    def map(self, fn, name: str = "map",
            parallelism: Optional[int] = None) -> "DataStream":
        return self._attach(name, MapOperator(fn), parallelism)

    def filter(self, pred, name: str = "filter",
               parallelism: Optional[int] = None) -> "DataStream":
        return self._attach(name, FilterOperator(pred), parallelism)

    def reduce(self, num_keys: int, reduce_fn=None, name: str = "reduce",
               parallelism: Optional[int] = None) -> "DataStream":
        import jax.numpy as jnp
        op = KeyedReduceOperator(num_keys=num_keys,
                                 reduce_fn=reduce_fn or jnp.add)
        if not self._keyed:
            raise ValueError("reduce requires key_by() first")
        return self._attach(name, op, parallelism)

    def window_count(self, num_keys: int, window_size: int,
                     name: str = "window",
                     parallelism: Optional[int] = None) -> "DataStream":
        if not self._keyed:
            raise ValueError("window_count requires key_by() first")
        return self._attach(
            name, TumblingWindowCountOperator(num_keys=num_keys,
                                              window_size=window_size),
            parallelism)

    def window_event_time(self, num_keys: int, window_size: int,
                          out_of_orderness: int = 0,
                          name: str = "event-window",
                          parallelism: Optional[int] = None
                          ) -> "DataStream":
        """Event-time tumbling window (watermark = pure fold over record
        timestamps with bounded out-of-orderness; see
        operators.EventTimeTumblingWindowOperator)."""
        from clonos_tpu.api.operators import EventTimeTumblingWindowOperator
        if not self._keyed:
            raise ValueError("window_event_time requires key_by() first")
        return self._attach(
            name, EventTimeTumblingWindowOperator(
                num_keys=num_keys, window_size=window_size,
                out_of_orderness=out_of_orderness), parallelism)

    def window_slide_event_time(self, num_keys: int, window_size: int,
                                slide: int, out_of_orderness: int = 0,
                                name: str = "sliding-window",
                                parallelism: Optional[int] = None
                                ) -> "DataStream":
        """Event-time sliding window (SlidingEventTimeWindows analog)."""
        from clonos_tpu.api.operators import SlidingEventTimeWindowOperator
        if not self._keyed:
            raise ValueError(
                "window_slide_event_time requires key_by() first")
        return self._attach(
            name, SlidingEventTimeWindowOperator(
                num_keys=num_keys, window_size=window_size, slide=slide,
                out_of_orderness=out_of_orderness), parallelism)

    def window_session(self, num_keys: int, gap: int,
                       out_of_orderness: int = 0,
                       name: str = "session-window",
                       parallelism: Optional[int] = None) -> "DataStream":
        """Event-time session window (EventTimeSessionWindows analog)."""
        from clonos_tpu.api.operators import SessionWindowOperator
        if not self._keyed:
            raise ValueError("window_session requires key_by() first")
        return self._attach(
            name, SessionWindowOperator(
                num_keys=num_keys, gap=gap,
                out_of_orderness=out_of_orderness), parallelism)

    def _attach2(self, other: "DataStream", name: str, op: Operator,
                 parallelism: Optional[int],
                 capacity: Optional[int] = None) -> "DataStream":
        """Two-input attachment: edge order is (self=left, other=right)."""
        p = parallelism or self._vertex.parallelism
        v = self._env.graph.add_vertex(name, op, p)
        cap = capacity or self._env.default_edge_capacity
        for side in (self, other):
            if side._keyed:
                part = PartitionType.HASH
            elif getattr(side, "_force_rebalance", False):
                part = PartitionType.REBALANCE
            elif side._vertex.parallelism == p:
                part = PartitionType.FORWARD
            else:
                part = PartitionType.REBALANCE
            self._env.graph.add_edge(side._vertex, v, part, cap)
        return DataStream(self._env, v)

    def union(self, other: "DataStream", capacity: Optional[int] = None,
              name: str = "union",
              parallelism: Optional[int] = None) -> "DataStream":
        cap = capacity or self._env.default_edge_capacity
        return self._attach2(other, name, UnionOperator(capacity=cap),
                             parallelism, cap)

    def join(self, other: "DataStream", num_keys: int, window: int,
             interval: int, capacity: Optional[int] = None,
             name: str = "join",
             parallelism: Optional[int] = None) -> "DataStream":
        """Keyed interval join: self is the left (buffered) side, other the
        right (probing) side. Both inputs must be key_by()'d."""
        if not (self._keyed and other._keyed):
            raise ValueError("join requires key_by() on both inputs")
        cap = capacity or self._env.default_edge_capacity
        op = IntervalJoinOperator(num_keys=num_keys, window=window,
                                  interval=interval, capacity=cap)
        return self._attach2(other, name, op, parallelism, cap)

    def rebalance(self) -> "DataStream":
        s = DataStream(self._env, self._vertex)
        s._force_rebalance = True
        return s

    def sink(self, name: str = "sink",
             parallelism: Optional[int] = None,
             transactional: bool = False) -> "DataStream":
        """``transactional=True`` routes emissions through the 2PC
        transaction log (exactly-once egress; runtime/txn.py)."""
        if transactional:
            from clonos_tpu.api.operators import TransactionalSinkOperator
            return self._attach(name, TransactionalSinkOperator(),
                                parallelism)
        return self._attach(name, SinkOperator(), parallelism)

    @property
    def vertex(self) -> JobVertex:
        return self._vertex


class StreamEnvironment:
    """Builder root (StreamExecutionEnvironment analog)."""

    def __init__(self, name: str = "job", num_key_groups: int = 128,
                 sharing_depth: int = -1, default_edge_capacity: int = 256):
        self.graph = JobGraph(name=name, num_key_groups=num_key_groups,
                              sharing_depth=sharing_depth)
        self.default_edge_capacity = default_edge_capacity

    def source(self, op: Operator, parallelism: int = 1,
               name: str = "source") -> DataStream:
        v = self.graph.add_vertex(name, op, parallelism)
        return DataStream(self, v)

    def synthetic_source(self, vocab: int, batch_size: int,
                         parallelism: int = 1, name: str = "source",
                         rate_limit: Optional[int] = None) -> DataStream:
        return self.source(
            SyntheticSource(vocab=vocab, batch_size=batch_size,
                            rate_limit=rate_limit),
            parallelism, name)

    def host_source(self, batch_size: int, parallelism: int = 1,
                    name: str = "host-source") -> DataStream:
        """Externally-fed source (register a FeedReader on the executor:
        ``executor.register_feed(vertex_id, reader)``)."""
        return self.source(HostFeedSource(batch_size=batch_size),
                           parallelism, name)

    def build(self) -> JobGraph:
        self.graph.validate()
        return self.graph
