"""Operator library: pure per-superstep batch transforms.

Capability analog of the reference's operator layer
(flink-streaming-java .../api/operators/AbstractStreamOperator.java,
StreamMap/StreamFilter, windowing/WindowOperator.java, StreamSource) —
re-imagined for TPU: an operator is a pair of pure functions

    init_state(parallelism)            -> state pytree, leading dim P
    process(state, batch, ctx)         -> (state, out_batch)

applied to a whole ``RecordBatch[P, B]`` per superstep. No per-record user
code: transforms are jnp expressions, keyed aggregation is scatter-add into
dense key tables, and windows fire on causal time carried in the step
context. Everything traces into one XLA program.

Time discipline (TPU-first): operators never read a clock. The current
processing time is a step *input* (``OpContext.time``) produced by the
causal time service — recorded as a TIMESTAMP determinant on the live path
and replayed from the log during recovery (reference
CausalTimeService.java:48-67). This makes every operator deterministic given
(state, batch, ctx).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.records import RecordBatch, empty, zero_invalid
from clonos_tpu.parallel import routing


class OpContext(NamedTuple):
    """Per-superstep inputs an operator may consume. All values are device
    scalars (or [P] vectors) fed by the executor — never host reads."""

    time: jnp.ndarray        # int32 scalar: causal processing time
    epoch: jnp.ndarray       # int32 scalar: current epoch id
    step: jnp.ndarray        # int32 scalar: superstep index within epoch
    rng_bits: jnp.ndarray    # int32 scalar: causal host-RNG draw for this step
    subtask: jnp.ndarray     # int32[P]: subtask indices (for vmapped ops)


class BlockContext(NamedTuple):
    """Step-batched context for :meth:`Operator.process_block`: the executor
    hands operators a whole block of K supersteps at once so their work
    compiles to a handful of large fused kernels instead of K small ones
    (the decisive TPU cost model — per-kernel launch dwarfs per-element
    work at stream batch sizes)."""

    times: jnp.ndarray       # int32[K]: causal time per superstep
    rng_bits: jnp.ndarray    # int32[K]: causal host-RNG draw per superstep
    epoch: jnp.ndarray       # int32 scalar: epoch id of the block
    step0: jnp.ndarray       # int32 scalar: global step index of block start
    subtask: jnp.ndarray     # int32[P]

    def at_step(self, k) -> OpContext:
        return OpContext(time=self.times[k], epoch=self.epoch,
                         step=self.step0 + jnp.asarray(k, jnp.int32),
                         rng_bits=self.rng_bits[k], subtask=self.subtask)


class Operator:
    """Base operator. Subclasses override ``init_state``/``process`` (the
    per-superstep semantics) and, for the hot path, ``process_block`` (the
    step-batched form, which must be bit-identical to scanning ``process``
    — tests/test_operators_block.py enforces this for the stock library)."""

    #: output batch capacity per subtask per superstep; None = same as input.
    out_capacity: Optional[int] = None

    #: Replay-padding contract: True iff running extra steps with
    #: all-invalid input batches and the last step's time/rng repeated
    #: leaves the operator state unchanged and emits only invalid records.
    #: Lets the replayer pad a partial tail block to the fixed block size
    #: (so warm standbys never compile on the failure path). Pure
    #: generators that advance state unconditionally (SyntheticSource)
    #: must set this False and accept one tail-shape compile instead.
    replay_pad_safe: bool = True

    #: Running-value contract: True iff every VALID output record carries
    #: the operator's updated keyed state for that record's key (Flink
    #: reduce semantics). Read replicas (runtime/serve.py) tail such
    #: operators to fence freshness by last-write-wins scatter of each
    #: sealed epoch's output ring — bit-identical to the owner's fence
    #: state by construction. Operators without the property fall back
    #: to checkpoint-only freshness on the read path.
    emits_running_value: bool = False

    def init_state(self, parallelism: int) -> Any:
        return ()

    def process(self, state: Any, batch: RecordBatch,
                ctx: OpContext) -> Tuple[Any, RecordBatch]:
        raise NotImplementedError

    def process_block(self, state: Any, batches: RecordBatch,
                      bctx: BlockContext) -> Tuple[Any, RecordBatch]:
        """Advance K supersteps at once. ``batches`` has leading dims
        ``[K, P, B]``; returns stacked outputs ``[K, P, out_cap]``.

        Default: ``lax.scan`` over :meth:`process` — always correct, pays
        per-step kernel costs; stock operators override with vectorized
        forms (prefix sums over the step axis)."""
        K = bctx.times.shape[0]

        def step(st, xs):
            b, k = xs
            return self.process(st, b, bctx.at_step(k))

        return jax.lax.scan(step, state,
                            (batches, jnp.arange(K, dtype=jnp.int32)))

    def static_out_keys(self) -> Optional[np.ndarray]:
        """The statically-known key of each output slot, or None when
        emission keys are dynamic. Dense-table emitters (window) return
        their key enumeration; the executor then replaces the downstream
        hash exchange with a compile-time gather plan
        (routing.StaticRoutePlan) — no sort, no scatter."""
        return None

    def rescale_keyed_state(self, state: Any, new_parallelism: int,
                            num_key_groups: int) -> Any:
        """Remap checkpointed state to a DIFFERENT parallelism by key
        ownership (reference StateAssignmentOperation +
        KeyGroupRangeAssignment: state is split/merged along key-group
        ranges). Dense-table operators implement it as sum-then-remask:
        per-key rows are disjoint across old subtasks (each only ever saw
        its own keys), so the global table is the subtask sum and each
        new subtask keeps the keys the new assignment routes to it.
        Operators without a keyed rescaling story raise."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support rescaling")


def rescale_dense_table(table: jnp.ndarray, new_parallelism: int,
                        num_key_groups: int,
                        fill: int = 0) -> jnp.ndarray:
    """Remap a dense keyed table ``[P, ..., K]`` to ``new_parallelism``:
    sum over the old subtask axis (rows are disjoint by key ownership)
    and keep, per new subtask, only the keys the new key-group
    assignment routes to it (``fill`` elsewhere — the operator's init
    value, what an untouched key holds)."""
    from clonos_tpu.parallel.routing import (key_group,
                                             subtask_for_key_group)
    nk = table.shape[-1]
    total = (table - fill).sum(axis=0) + fill
    kg = key_group(jnp.arange(nk, dtype=jnp.int32), num_key_groups)
    owner = subtask_for_key_group(kg, new_parallelism, num_key_groups)
    sub = jnp.arange(new_parallelism, dtype=jnp.int32)
    mask = (owner[None, :] == sub[:, None]).reshape(
        (new_parallelism,) + (1,) * (total.ndim - 1) + (nk,))
    return jnp.where(mask, total[None], fill)


class TwoInputOperator(Operator):
    """Base for vertices with two input edges (ConnectedStreams /
    TwoInputStreamOperator analog, flink-streaming-java
    .../api/operators/TwoInputStreamOperator.java).

    TPU-first note on ORDER determinants: the reference logs which channel
    each consumed buffer came from because its task threads race on input
    queues (CausalBufferOrderService.java:48). The lockstep superstep
    consumes BOTH inputs' pending batch every step, so the interleaving
    nondeterminism is structurally eliminated — ``process2`` receives both
    batches and any merge it performs is a pure function. The ORDER
    determinant still records the (degenerate) selection for wire/protocol
    parity."""

    def process2(self, state: Any, left: RecordBatch, right: RecordBatch,
                 ctx: OpContext) -> Tuple[Any, RecordBatch]:
        raise NotImplementedError

    def process(self, state, batch, ctx):
        raise TypeError("TwoInputOperator requires process2 with two inputs")

    def process_block(self, state: Any, batches: Tuple[RecordBatch,
                                                       RecordBatch],
                      bctx: BlockContext) -> Tuple[Any, RecordBatch]:
        """``batches`` is a (left, right) pair of ``[K, P, B]`` stacks."""
        K = bctx.times.shape[0]

        def step(st, xs):
            (l, r), k = xs
            return self.process2(st, l, r, bctx.at_step(k))

        return jax.lax.scan(step, state,
                            (batches, jnp.arange(K, dtype=jnp.int32)))


@dataclasses.dataclass
class MapOperator(Operator):
    """Elementwise transform: fn(keys, values, timestamps) -> same triple.
    (StreamMap equivalent; fn is a traced jnp expression, not per-record.)"""

    fn: Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray],
                 Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]

    def process(self, state, batch, ctx):
        k, v, t = self.fn(batch.keys, batch.values, batch.timestamps)
        return state, zero_invalid(RecordBatch(k, v, t, batch.valid))

    def process_block(self, state, batches, bctx):
        # Stateless elementwise fn: applies to the whole [K, P, B] stack.
        return self.process(state, batches, None)


@dataclasses.dataclass
class FilterOperator(Operator):
    """Keep records where pred(keys, values, timestamps) — mask update only;
    compaction happens at the next exchange (StreamFilter equivalent)."""

    pred: Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]

    def process(self, state, batch, ctx):
        keep = batch.valid & self.pred(batch.keys, batch.values, batch.timestamps)
        return state, zero_invalid(batch._replace(valid=keep))

    def process_block(self, state, batches, bctx):
        return self.process(state, batches, None)


@dataclasses.dataclass
class SyntheticSource(Operator):
    """On-device record generator (benchmark source; StreamSource analog).

    Emits ``batch_size`` records per superstep per subtask with keys drawn
    from ``[0, vocab)`` by a counter hash — deterministic given the carried
    sequence counter, so replay regenerates identical data without logging
    the payloads (the in-flight log covers the *downstream* loss case).
    """

    vocab: int
    batch_size: int
    rate_limit: Optional[int] = None  # records/superstep cap (None = full)

    #: generates unconditionally per step — padding would advance ``seq``.
    replay_pad_safe = False

    @property
    def out_capacity(self):  # type: ignore[override]
        return self.batch_size

    def init_state(self, parallelism: int):
        return {"seq": jnp.zeros((parallelism,), jnp.int32)}

    #: key-mix stride; must exceed any parallelism so (seq, subtask) pairs
    #: stay unique — and must NOT depend on the state's leading dim, which
    #: is 1 when a lone subtask is being replayed after a failure.
    SUBTASK_STRIDE = 1 << 10

    def process(self, state, batch, ctx):
        p = state["seq"].shape[0]
        b = self.batch_size
        lane = jnp.arange(b, dtype=jnp.int32)
        seq = state["seq"][:, None] + lane[None, :]              # [P, B]
        mix = seq * self.SUBTASK_STRIDE + ctx.subtask[:, None]   # global unique
        keys = (routing.hash32(mix) % jnp.uint32(self.vocab)).astype(jnp.int32)
        n = b if self.rate_limit is None else min(b, self.rate_limit)
        valid = jnp.broadcast_to(lane < n, (p, b))
        ts = jnp.broadcast_to(ctx.time, (p, b)).astype(jnp.int32)
        out = zero_invalid(RecordBatch(keys, jnp.ones((p, b), jnp.int32), ts, valid))
        return {"seq": state["seq"] + n}, out

    def process_block(self, state, batches, bctx):
        # The sequence counter advances by exactly n per step, so the whole
        # block's keys are a closed form of (seq0, step index) — one kernel.
        p = state["seq"].shape[0]
        b = self.batch_size
        K = bctx.times.shape[0]
        n = b if self.rate_limit is None else min(b, self.rate_limit)
        lane = jnp.arange(b, dtype=jnp.int32)
        step = jnp.arange(K, dtype=jnp.int32)
        seq = (state["seq"][None, :, None] + step[:, None, None] * n
               + lane[None, None, :])                            # [K, P, B]
        mix = seq * self.SUBTASK_STRIDE + bctx.subtask[None, :, None]
        keys = (routing.hash32(mix) % jnp.uint32(self.vocab)).astype(jnp.int32)
        valid = jnp.broadcast_to(lane[None, None, :] < n, (K, p, b))
        ts = jnp.broadcast_to(bctx.times[:, None, None], (K, p, b)
                              ).astype(jnp.int32)
        out = zero_invalid(RecordBatch(keys, jnp.ones((K, p, b), jnp.int32),
                                       ts, valid))
        return {"seq": state["seq"] + n * K}, out


@dataclasses.dataclass
class KeyedReduceOperator(Operator):
    """Running keyed reduce over a dense key table (keyed-state analog of the
    reference's HeapKeyedStateBackend ValueState + ReduceFunction).

    State is ``acc[P, num_keys]``; each subtask only ever sees keys routed to
    it by the upstream HASH exchange, so tables never conflict. Emits the
    updated running value for every input record (Flink reduce semantics).
    """

    num_keys: int
    reduce_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray] = jnp.add
    init_value: int = 0
    # out_vals = new_acc[b.keys] for valid records below — the running
    # value — so read replicas can tail this operator's output rings.
    emits_running_value = True

    def init_state(self, parallelism: int):
        return {"acc": jnp.full((parallelism, self.num_keys), self.init_value,
                                jnp.int32)}

    def rescale_keyed_state(self, state, new_parallelism, num_key_groups):
        return {"acc": rescale_dense_table(
            state["acc"], new_parallelism, num_key_groups,
            fill=self.init_value)}

    def process(self, state, batch, ctx):
        def one(acc, b: RecordBatch):
            # Sequential fold per slot is wrong for non-commutative fns under
            # scatter; restrict to associative+commutative reduce_fn (doc'd).
            contrib = jnp.zeros_like(acc).at[b.keys].add(
                jnp.where(b.valid, b.values, 0), mode="drop")
            # A key is touched iff any VALID record carries it; scatter-add
            # of the mask (scatter-set with duplicate keys is unordered —
            # an invalid record zeroed to key 0 must not untouch key 0).
            touched = jnp.zeros(acc.shape, jnp.int32).at[b.keys].add(
                b.valid.astype(jnp.int32), mode="drop") > 0
            new_acc = jnp.where(touched, self.reduce_fn(acc, contrib), acc)
            out_vals = jnp.where(b.valid, new_acc[b.keys], 0)
            return new_acc, zero_invalid(b._replace(values=out_vals))
        new_acc, out = jax.vmap(one)(state["acc"], batch)
        return {"acc": new_acc}, out

    def process_block(self, state, batches, bctx):
        # Vectorized form is exact only for the additive default (the prefix
        # over steps must distribute); other reduce_fns take the scan path.
        if self.reduce_fn is not jnp.add:
            return super().process_block(state, batches, bctx)
        from clonos_tpu.ops.histogram import keyed_hist
        K, p, _ = batches.keys.shape
        nk = self.num_keys
        acc0 = state["acc"]                               # [P, nk]
        contrib, _ = keyed_hist(batches.keys, batches.values,
                                batches.valid, nk,
                                want_counts=False)        # [K, P, nk]
        cum = jnp.cumsum(contrib, axis=0)                 # inclusive prefix
        acc_end = acc0[None] + cum                        # [K, P, nk]
        out_vals = jnp.where(
            batches.valid,
            jnp.take_along_axis(
                acc_end.reshape(K * p, nk),
                batches.keys.reshape(K * p, -1), axis=1
            ).reshape(batches.keys.shape), 0)
        return ({"acc": acc0 + cum[-1]},
                zero_invalid(batches._replace(values=out_vals)))

    def process_block_static_keys(self, state, batches, bctx,
                                  slot_keys: np.ndarray):
        """Fast path when the input arrives over a StaticRoutePlan edge:
        ``slot_keys[p, b]`` is the compile-time key of input slot (p, b)
        (-1 = never mapped). The per-step histogram then needs no dynamic
        scatter — each key's contributions sit at statically-known slots,
        so ``contrib`` is a handful of static gathers (one per producer
        occurrence), and emission is a static gather back. Bit-identical
        to :meth:`process_block` (integer adds in the same association).
        """
        if self.reduce_fn is not jnp.add:
            return self.process_block(state, batches, bctx)
        K, p, B = batches.keys.shape
        nk = self.num_keys
        sk = np.asarray(slot_keys)
        if sk.shape != (p, B):
            raise ValueError(f"slot_keys shape {sk.shape} != {(p, B)}")
        # Static inverted index: slots carrying key n on subtask q.
        occ = [[[] for _ in range(nk)] for _ in range(p)]
        for q in range(p):
            for b in range(B):
                k = int(sk[q, b])
                if 0 <= k < nk:
                    occ[q][k].append(b)
        S = max((len(o) for row in occ for o in row), default=0)
        S = max(S, 1)
        idx = np.full((p, nk, S), B, np.int32)        # B = zero-pad column
        for q in range(p):
            for n in range(nk):
                for s, b in enumerate(occ[q][n]):
                    idx[q, n, s] = b
        vals = jnp.where(batches.valid, batches.values, 0)
        vpad = jnp.pad(vals, ((0, 0), (0, 0), (0, 1)))    # [K, P, B+1]
        pp = np.arange(p)[:, None]
        contrib = vpad[:, pp, idx[:, :, 0]]
        for s in range(1, S):
            contrib = contrib + vpad[:, pp, idx[:, :, s]]  # [K, P, nk]
        cum = jnp.cumsum(contrib, axis=0)
        acc0 = state["acc"]
        acc_end = acc0[None] + cum
        key_of_slot = np.clip(sk, 0, nk - 1)
        out_vals = jnp.where(
            batches.valid,
            acc_end[:, pp, key_of_slot], 0)
        return ({"acc": acc0 + cum[-1]},
                zero_invalid(batches._replace(values=out_vals)))


@dataclasses.dataclass
class TumblingWindowCountOperator(Operator):
    """Tumbling processing-time windowed count/sum per key
    (WindowOperator + aggregate equivalent; the SocketWindowWordCount shape).

    ``window_size`` is in causal-time units. State: dense ``acc[P, K]`` and
    the current window id per subtask. When ``ctx.time`` crosses a window
    boundary, emits one record per key with a nonzero accumulator
    (key, aggregate, window_end_time) and resets. Emission capacity is
    ``num_keys`` (dense scan of the table — static shape).
    """

    num_keys: int
    window_size: int

    @property
    def out_capacity(self):  # type: ignore[override]
        return self.num_keys

    def init_state(self, parallelism: int):
        return {
            "acc": jnp.zeros((parallelism, self.num_keys), jnp.int32),
            "window": jnp.zeros((parallelism,), jnp.int32),
        }

    def process(self, state, batch, ctx):
        w_now = (ctx.time // self.window_size).astype(jnp.int32)

        def one(acc, window, b: RecordBatch):
            fire = w_now > window
            window_end = (window + 1) * self.window_size
            keys = jnp.arange(self.num_keys, dtype=jnp.int32)
            out = RecordBatch(
                keys=keys,
                values=acc,
                timestamps=jnp.full((self.num_keys,), 1, jnp.int32) * window_end,
                valid=fire & (acc != 0),
            )
            acc = jnp.where(fire, 0, acc)
            # Accumulate this superstep's records into the (possibly fresh)
            # window.
            acc = acc.at[b.keys].add(jnp.where(b.valid, b.values, 0), mode="drop")
            window = jnp.where(fire, w_now, window)
            return acc, window, zero_invalid(out)

        acc, window, out = jax.vmap(one)(state["acc"], state["window"], batch)
        return {"acc": acc, "window": window}, out

    def process_block(self, state, batches, bctx):
        # Step-batched form: window-id evolution is a running max of the
        # per-step window ids; accumulator segments between fires are
        # differences of an inclusive prefix sum; the emission at a fire
        # step is the segment ending at the previous step. All exact int32.
        K, p, _ = batches.keys.shape
        nk = self.num_keys
        size = self.window_size
        w_now = (bctx.times // size).astype(jnp.int32)            # [K]
        w0 = state["window"]                                      # [P]
        acc0 = state["acc"]                                       # [P, nk]
        rm = jax.lax.associative_scan(jnp.maximum, w_now)         # incl [K]
        neg_inf = jnp.asarray(-(2 ** 31) + 1, jnp.int32)
        rm_excl = jnp.concatenate([neg_inf[None], rm[:-1]])
        window_pre = jnp.maximum(w0[None, :], rm_excl[:, None])   # [K, P]
        fire = w_now[:, None] > window_pre                        # [K, P]

        from clonos_tpu.ops.histogram import keyed_hist
        contrib, _ = keyed_hist(batches.keys, batches.values,
                                batches.valid, nk,
                                want_counts=False)                # [K, P, nk]
        cum = jnp.cumsum(contrib, axis=0)                         # [K, P, nk]
        cum_excl = cum - contrib

        kidx = jnp.arange(K, dtype=jnp.int32)[:, None]
        lf = jax.lax.associative_scan(                            # [K, P]
            jnp.maximum, jnp.where(fire, kidx, -1), axis=0)
        from clonos_tpu.ops.matops import onehot_gather_rows
        seg_base = onehot_gather_rows(cum_excl, jnp.clip(lf, 0, K - 1))
        acc_end = jnp.where(lf[:, :, None] >= 0, cum - seg_base,
                            acc0[None] + cum)                     # [K, P, nk]
        emit = jnp.concatenate([acc0[None], acc_end[:-1]], axis=0)

        keys = jnp.broadcast_to(jnp.arange(nk, dtype=jnp.int32)[None, None, :],
                                (K, p, nk))
        window_end = (window_pre + 1) * size                      # [K, P]
        out = zero_invalid(RecordBatch(
            keys=keys, values=emit,
            timestamps=jnp.broadcast_to(window_end[:, :, None], (K, p, nk)
                                        ).astype(jnp.int32),
            valid=fire[:, :, None] & (emit != 0)))
        return ({"acc": acc_end[-1],
                 "window": jnp.maximum(w0, rm[-1])}, out)

    def rescale_keyed_state(self, state, new_parallelism, num_key_groups):
        # Window ids are lockstep across subtasks (driven by shared
        # causal time): carry the max forward.
        return {"acc": rescale_dense_table(state["acc"], new_parallelism,
                                           num_key_groups),
                "window": jnp.broadcast_to(state["window"].max(),
                                           (new_parallelism,))}

    def static_out_keys(self) -> Optional[np.ndarray]:
        # Dense table emission: slot i always carries key i.
        return np.arange(self.num_keys, dtype=np.int32)


#: free-slot sentinel for open-window tables; far below any reachable
#: window id (ids are event_ts // size), and safe in guarded arithmetic.
_NO_WINDOW = -(2 ** 30)


@dataclasses.dataclass
class EventTimeTumblingWindowOperator(Operator):
    """Event-time tumbling windowed sum per key with watermark-driven
    firing (WindowOperator + EventTimeTrigger analog; reference
    flink-streaming-java .../windowing/WindowOperator.java with
    watermarks from StreamSourceContexts.java:180-187).

    TPU-first watermark discipline: the watermark is a PURE FOLD over the
    record timestamps flowing through this operator —
    ``wm = max(event_ts seen) - out_of_orderness`` — not a timer race.
    That makes it deterministic given the inputs, so recovery replays it
    bit-identically with **no watermark determinant at all** (the
    reference must route watermark generation through causal time because
    its per-channel arrival interleaving races; the lockstep superstep
    eliminates the race structurally).

    Batched-watermark discipline: the watermark advances once per
    superstep, BEFORE the step's records are assigned — so records of one
    superstep whose timestamps trail the step's own maximum by more than
    ``out_of_orderness`` are late-dropped. Set ``out_of_orderness`` to at
    least the expected intra-superstep timestamp spread (the reference's
    per-record watermark interleaving has the same knob, just at record
    granularity).

    State per subtask: ``open_windows`` accumulator slots ``acc[W, nk]``
    with absolute window ids ``win[W]`` (-1 = free), plus ``max_ts``.
    A record with ts in a window older than every open slot (arrived
    after its window fired, or slots exhausted) is a LATE DROP — counted
    in ``late`` like the reference's lateness side-output. Windows whose
    end <= watermark fire: one record per key with a nonzero sum,
    timestamped with the window end.
    """

    num_keys: int
    window_size: int
    out_of_orderness: int = 0
    open_windows: int = 2

    def __post_init__(self):
        # After fire-first, open window ids span at most
        # out_of_orderness // window_size + 1 consecutive values; one
        # spare slot keeps the (rw % W) placement collision-free.
        need = self.out_of_orderness // self.window_size + 2
        self.open_windows = max(self.open_windows, need)

    @property
    def out_capacity(self):  # type: ignore[override]
        # All open windows may fire in one step.
        return self.num_keys * self.open_windows

    def init_state(self, parallelism: int):
        w = self.open_windows
        return {
            "acc": jnp.zeros((parallelism, w, self.num_keys), jnp.int32),
            "win": jnp.full((parallelism, w), _NO_WINDOW, jnp.int32),
            "max_ts": jnp.full((parallelism,), -(2 ** 31) + 1, jnp.int32),
            "late": jnp.zeros((parallelism,), jnp.int32),
        }

    def process(self, state, batch, ctx):
        nk, w, size = self.num_keys, self.open_windows, self.window_size

        def one(acc, win, max_ts, late, b: RecordBatch):
            # Advance the watermark from this step's data (pure fold).
            step_max = jnp.max(jnp.where(b.valid, b.timestamps,
                                         -(2 ** 31) + 1))
            max_ts = jnp.maximum(max_ts, step_max)
            wm = max_ts - self.out_of_orderness
            # FIRE FIRST: every open window with end <= wm closes, freeing
            # slots so this step's newest windows can't collide with
            # stale ones (a window completed by this step's records emits
            # next step — deterministic one-step emission latency).
            open_ = win != _NO_WINDOW
            win_end = (jnp.where(open_, win, 0) + 1) * size   # [W]
            fire = open_ & (win_end <= wm)                # [W]
            keys = jnp.broadcast_to(
                jnp.arange(nk, dtype=jnp.int32)[None, :], (w, nk))
            out = RecordBatch(
                keys=keys.reshape(-1),
                values=acc.reshape(-1),
                timestamps=jnp.broadcast_to(
                    win_end[:, None], (w, nk)).reshape(-1),
                valid=(fire[:, None] & (acc != 0)).reshape(-1))
            acc = jnp.where(fire[:, None], 0, acc)
            win = jnp.where(fire, _NO_WINDOW, win)
            # Assign records to absolute windows.
            rw = b.timestamps // size          # jnp // floors already
            closed = (rw + 1) * size <= wm                # behind the wm
            slot = rw % w
            slot_win = win[slot]                          # [B]
            ok = b.valid & ~closed & ((slot_win == rw)
                                      | (slot_win == _NO_WINDOW))
            late = late + jnp.sum((b.valid & ~ok).astype(jnp.int32))
            win = win.at[slot].max(jnp.where(ok, rw, _NO_WINDOW),
                                   mode="drop")
            acc = acc.at[slot, jnp.clip(b.keys, 0, nk - 1)].add(
                jnp.where(ok, b.values, 0), mode="drop")
            return acc, win, max_ts, late, zero_invalid(out)

        acc, win, max_ts, late, out = jax.vmap(one)(
            state["acc"], state["win"], state["max_ts"], state["late"],
            batch)
        return ({"acc": acc, "win": win, "max_ts": max_ts,
                 "late": late}, out)


@dataclasses.dataclass
class SlidingEventTimeWindowOperator(Operator):
    """Event-time SLIDING windowed sum per key: each record contributes to
    ``size // slide`` consecutive windows (WindowOperator +
    SlidingEventTimeWindows analog). Window id = its start // slide.
    Same pure-fold watermark discipline as the tumbling variant."""

    num_keys: int
    window_size: int
    slide: int
    out_of_orderness: int = 0
    open_windows: int = 4

    def __post_init__(self):
        if self.window_size % self.slide:
            raise ValueError("window_size must be a multiple of slide")
        need = (self.out_of_orderness + self.window_size) // self.slide + 2
        self.open_windows = max(self.open_windows, need)

    @property
    def out_capacity(self):  # type: ignore[override]
        return self.num_keys * self.open_windows

    def init_state(self, parallelism: int):
        w = self.open_windows
        return {
            "acc": jnp.zeros((parallelism, w, self.num_keys), jnp.int32),
            "win": jnp.full((parallelism, w), _NO_WINDOW, jnp.int32),
            "max_ts": jnp.full((parallelism,), -(2 ** 31) + 1, jnp.int32),
            "late": jnp.zeros((parallelism,), jnp.int32),
        }

    def process(self, state, batch, ctx):
        nk, w = self.num_keys, self.open_windows
        size, slide = self.window_size, self.slide
        per = size // slide

        def one(acc, win, max_ts, late, b: RecordBatch):
            step_max = jnp.max(jnp.where(b.valid, b.timestamps,
                                         -(2 ** 31) + 1))
            max_ts = jnp.maximum(max_ts, step_max)
            wm = max_ts - self.out_of_orderness
            # Fire first (see the tumbling variant).
            open_ = win != _NO_WINDOW
            win_end = jnp.where(open_, win, 0) * slide + size   # [W]
            fire = open_ & (win_end <= wm)
            keys = jnp.broadcast_to(
                jnp.arange(nk, dtype=jnp.int32)[None, :], (w, nk))
            out = RecordBatch(
                keys=keys.reshape(-1),
                values=acc.reshape(-1),
                timestamps=jnp.broadcast_to(
                    win_end[:, None], (w, nk)).reshape(-1),
                valid=(fire[:, None] & (acc != 0)).reshape(-1))
            acc = jnp.where(fire[:, None], 0, acc)
            win = jnp.where(fire, _NO_WINDOW, win)
            # Newest window containing ts starts at floor(ts/slide)*slide;
            # the record is in windows starting there minus j*slide.
            base = b.timestamps // slide       # jnp // floors already
            ok_any = jnp.zeros_like(b.valid)
            for j in range(per):
                rw = base - j                              # window id
                closed = rw * slide + size <= wm
                slot = rw % w
                slot_win = win[slot]
                ok = b.valid & ~closed & ((slot_win == rw)
                                          | (slot_win == _NO_WINDOW))
                ok_any = ok_any | ok
                win = win.at[slot].max(jnp.where(ok, rw, _NO_WINDOW),
                                       mode="drop")
                acc = acc.at[slot, jnp.clip(b.keys, 0, nk - 1)].add(
                    jnp.where(ok, b.values, 0), mode="drop")
            # One late increment per record dropped from ALL its windows
            # (reference numLateRecordsDropped counts elements, not
            # (element, window) pairs).
            late = late + jnp.sum((b.valid & ~ok_any).astype(jnp.int32))
            return acc, win, max_ts, late, zero_invalid(out)

        acc, win, max_ts, late, out = jax.vmap(one)(
            state["acc"], state["win"], state["max_ts"], state["late"],
            batch)
        return ({"acc": acc, "win": win, "max_ts": max_ts,
                 "late": late}, out)


@dataclasses.dataclass
class SessionWindowOperator(Operator):
    """Event-time session windows per key: a session absorbs records
    within ``gap`` of its current end and fires when the watermark passes
    end + gap (EventTimeSessionWindows analog, dense single-open-session
    form: one open session per key — a late record for a closed session
    is a late drop)."""

    num_keys: int
    gap: int
    out_of_orderness: int = 0

    @property
    def out_capacity(self):  # type: ignore[override]
        return self.num_keys

    def init_state(self, parallelism: int):
        nk = self.num_keys
        return {
            "acc": jnp.zeros((parallelism, nk), jnp.int32),
            "end": jnp.full((parallelism, nk), -(2 ** 31) + 1, jnp.int32),
            "max_ts": jnp.full((parallelism,), -(2 ** 31) + 1, jnp.int32),
            "late": jnp.zeros((parallelism,), jnp.int32),
        }

    def process(self, state, batch, ctx):
        nk = self.num_keys

        def one(acc, end, max_ts, late, b: RecordBatch):
            step_max = jnp.max(jnp.where(b.valid, b.timestamps,
                                         -(2 ** 31) + 1))
            max_ts = jnp.maximum(max_ts, step_max)
            wm = max_ts - self.out_of_orderness
            # FIRE FIRST: sessions whose (end + gap) the watermark passed
            # close now, so a later record more than ``gap`` past a stale
            # end starts a FRESH session instead of merging across the
            # gap (the docstring's absorb-within-gap contract).
            live = end > -(2 ** 31) + 1
            # A session CLOSES whenever the watermark passes end+gap —
            # even with a zero-sum accumulator (which merely emits
            # nothing); gating the slot reset on acc != 0 would wedge the
            # key forever after a zero-valued session.
            fire = live & (end + self.gap <= wm)
            out = RecordBatch(
                keys=jnp.arange(nk, dtype=jnp.int32),
                values=acc,
                timestamps=end + self.gap,
                valid=fire & (acc != 0))
            acc = jnp.where(fire, 0, acc)
            end = jnp.where(fire, -(2 ** 31) + 1, end)
            live = live & ~fire
            k = jnp.clip(b.keys, 0, nk - 1)
            # Absorb: within ``gap`` of the open session's end, or into an
            # empty slot if the record's own session wouldn't already have
            # closed (end+gap = ts+gap must still be ahead of the
            # watermark). Anything else — behind the closed frontier, or
            # racing ahead of its key's un-fired session within one
            # superstep — is a late drop.
            ok = b.valid & jnp.where(
                live[k],
                b.timestamps - end[k] <= self.gap,
                b.timestamps + self.gap > wm)
            late = late + jnp.sum((b.valid & ~ok).astype(jnp.int32))
            acc = acc.at[k].add(jnp.where(ok, b.values, 0), mode="drop")
            end = end.at[k].max(jnp.where(ok, b.timestamps,
                                          -(2 ** 31) + 1), mode="drop")
            return acc, end, max_ts, late, zero_invalid(out)

        acc, end, max_ts, late, out = jax.vmap(one)(
            state["acc"], state["end"], state["max_ts"], state["late"],
            batch)
        return ({"acc": acc, "end": end, "max_ts": max_ts,
                 "late": late}, out)


@dataclasses.dataclass
class UnionOperator(TwoInputOperator):
    """Merge two streams: left records first, then right, compacted into a
    fixed output capacity (the union / ConnectedStreams.map-same-type
    shape). Deterministic concatenation order replaces the reference's
    arrival-order race."""

    capacity: int

    @property
    def out_capacity(self):  # type: ignore[override]
        return self.capacity

    def process2(self, state, left, right, ctx):
        def one(l: RecordBatch, r: RecordBatch):
            keys = jnp.concatenate([l.keys, r.keys])
            vals = jnp.concatenate([l.values, r.values])
            ts = jnp.concatenate([l.timestamps, r.timestamps])
            valid = jnp.concatenate([l.valid, r.valid])
            # Compact valid records to the front (stable); anything past
            # ``capacity`` live records is a (deterministic) overflow drop.
            order = jnp.argsort(~valid, stable=True)
            take = order[: self.capacity]
            return zero_invalid(RecordBatch(
                keys[take], vals[take], ts[take], valid[take]))
        return state, jax.vmap(one)(left, right)

    def process_block(self, state, batches, bctx):
        # Stateless: flatten [K, P] into one vmapped batch dim.
        left, right = batches
        K, p = left.keys.shape[:2]
        rs = lambda b: jax.tree_util.tree_map(
            lambda x: x.reshape((K * p,) + x.shape[2:]), b)
        _, out = self.process2(state, rs(left), rs(right), None)
        return state, jax.tree_util.tree_map(
            lambda x: x.reshape((K, p) + x.shape[1:]), out)


@dataclasses.dataclass
class IntervalJoinOperator(TwoInputOperator):
    """Keyed stream-stream join (the NEXMark-style join shape,
    BASELINE config #5; reference analog: IntervalJoinOperator /
    flink-libraries join machinery re-imagined dense).

    State per subtask: for each key, a ring of the last ``window`` left
    records (value, timestamp). Each right record joins against all
    retained left records of its key with |ts_l - ts_r| <= interval,
    emitting (key, combine(vl, vr), ts_r). Dense tables: ``[P, K, W]``.
    Emission capacity bounds matches per step (static shape; overflow
    drops are deterministic)."""

    num_keys: int
    window: int               # retained left records per key
    interval: int             # max |ts_left - ts_right|
    capacity: int             # output capacity per subtask per step

    @property
    def out_capacity(self):  # type: ignore[override]
        return self.capacity

    def init_state(self, parallelism: int):
        k, w = self.num_keys, self.window
        return {
            "lv": jnp.zeros((parallelism, k, w), jnp.int32),   # left values
            "lt": jnp.zeros((parallelism, k, w), jnp.int32),   # left ts
            "lm": jnp.zeros((parallelism, k, w), jnp.bool_),   # live mask
            "cursor": jnp.zeros((parallelism, k), jnp.int32),  # ring cursor
        }

    def process2(self, state, left, right, ctx):
        k, w, cap = self.num_keys, self.window, self.capacity

        def one(lv, lt, lm, cursor, l: RecordBatch, r: RecordBatch):
            # Insert the whole left batch at once. A record's ring slot is
            # cursor[key] + its per-key arrival rank (a running bucket
            # count — same counting trick as the routing exchange, no
            # per-record scan); only the last ``w`` records of a key
            # survive a single batch (earlier ones would be overwritten
            # by the sequential semantics anyway), which also makes every
            # scatter destination unique.
            lk = jnp.clip(l.keys, 0, k - 1)
            onehot = (l.valid[:, None]
                      & (lk[:, None] == jnp.arange(k, dtype=jnp.int32)))
            cum = jnp.cumsum(onehot.astype(jnp.int32), axis=0)  # [B, K]
            rank = jnp.take_along_axis(cum, lk[:, None], 1)[:, 0] - 1
            total = cum[-1]                                     # [K]
            keep = l.valid & (total[lk] - 1 - rank < w)
            slot = (cursor[lk] + rank) % w
            row = jnp.where(keep, lk, k)          # k = drop row
            lv = lv.at[row, slot].set(l.values, mode="drop")
            lt = lt.at[row, slot].set(l.timestamps, mode="drop")
            lm = lm.at[row, slot].set(True, mode="drop")
            cursor = cursor + total

            # Join each right record against its key's ring: [B_r, W] pairs.
            rk = jnp.clip(r.keys, 0, k - 1)
            cand_v = lv[rk]                       # [B_r, W]
            cand_t = lt[rk]
            cand_m = lm[rk] & r.valid[:, None]
            match = cand_m & (jnp.abs(cand_t - r.timestamps[:, None])
                              <= self.interval)
            out_keys = jnp.broadcast_to(r.keys[:, None], match.shape)
            out_vals = cand_v + r.values[:, None]
            out_ts = jnp.broadcast_to(r.timestamps[:, None], match.shape)
            flat_n = match.size
            fk = out_keys.reshape(flat_n)
            fv = out_vals.reshape(flat_n)
            ft = out_ts.reshape(flat_n)
            fm = match.reshape(flat_n)
            # Compact matches to the front by arrival rank (cumsum, not
            # argsort); first ``cap`` survive, deterministically.
            pos = jnp.cumsum(fm.astype(jnp.int32)) - 1
            keep2 = fm & (pos < cap)
            dst = jnp.where(keep2, pos, cap)
            g = lambda src, z: jnp.zeros((cap + 1,), z).at[dst].set(
                src, mode="drop")[:cap]
            return lv, lt, lm, cursor, zero_invalid(RecordBatch(
                g(fk, jnp.int32), g(fv, jnp.int32), g(ft, jnp.int32),
                g(fm, jnp.bool_)))

        lv, lt, lm, cursor, out = jax.vmap(one)(
            state["lv"], state["lt"], state["lm"], state["cursor"],
            left, right)
        return {"lv": lv, "lt": lt, "lm": lm, "cursor": cursor}, out

    def process_block(self, state, batches, bctx):
        """Grouped step-batched form: G supersteps are fused per scan
        iteration (the per-step scan cost ~3ms/step at 128-task bench
        shapes — a 20k-step replay took a minute of pure scan overhead).

        Within a group everything is rank arithmetic, bit-identical to
        the sequential semantics: a left record's ring slot is its
        GLOBAL arrival index mod w (cursor carries the global count), so
        a right record's slot-j candidate is the latest group-local left
        with rank ≡ (j - cursor) mod w below its through-count — gathered
        from a group-local time-indexed table — falling back to the
        carried ring slot j for pre-group arrivals. Join outputs keep
        process2's exact (right-slot, ring-slot) emission order and
        per-step compaction."""
        left, right = batches
        K, P, B = left.keys.shape
        B2 = right.keys.shape[2]
        nk, w, cap = self.num_keys, self.window, self.capacity
        # Group size bounded by the [P, nk, G*B] table scratch.
        budget = 128 << 20
        per = P * nk * B * 4 * 3
        gmax = max(1, min(64, budget // max(per, 1)))
        G = 1
        for d in range(int(gmax), 0, -1):
            if K % d == 0:
                G = d
                break
        if G == 1:
            return TwoInputOperator.process_block(self, state, batches,
                                                  bctx)
        n = G * B
        ks = jnp.arange(nk, dtype=jnp.int32)

        def one(lv, lt, lm, cur, l, r):
            # l fields [G, B] (one lane); flatten in (step, slot) order —
            # the sequential insert order.
            lk = jnp.clip(l.keys, 0, nk - 1).reshape(n)
            lvalid = l.valid.reshape(n)
            oh = lvalid[:, None] & (lk[:, None] == ks[None, :])
            cum = jnp.cumsum(oh.astype(jnp.int32), axis=0)   # [n, nk] incl
            rank = jnp.take_along_axis(cum, lk[:, None], 1)[:, 0] - 1
            rank = jnp.where(lvalid, rank, n)                # n = drop row
            total = cum[-1]                                  # [nk]
            # Time-indexed group table: left record with (key, rank).
            Tv = jnp.zeros((nk, n), jnp.int32).at[lk, rank].set(
                l.values.reshape(n), mode="drop")
            Tt = jnp.zeros((nk, n), jnp.int32).at[lk, rank].set(
                l.timestamps.reshape(n), mode="drop")
            # Lefts of key k seen through step g (inclusive).
            through = cum.reshape(G, B, nk)[:, -1]           # [G, nk]

            rk = jnp.clip(r.keys, 0, nk - 1)                 # [G, B2]
            hi = jnp.take_along_axis(through, rk, 1)         # [G, B2]
            c0 = cur[rk]                                     # [G, B2]
            js = jnp.arange(w, dtype=jnp.int32)
            hib = hi[..., None]
            tmod = (js[None, None, :] - c0[..., None]) % w   # [G, B2, w]
            rc = hib - 1 - ((hib - 1 - tmod) % w)
            use_g = (hib > 0) & (rc >= 0)
            rc_s = jnp.clip(rc, 0, n - 1)
            rkb = rk[..., None]
            cand_v = jnp.where(use_g, Tv[rkb, rc_s], lv[rk])
            cand_t = jnp.where(use_g, Tt[rkb, rc_s], lt[rk])
            cand_m = jnp.where(use_g, use_g, lm[rk]) & r.valid[..., None]
            match = cand_m & (jnp.abs(cand_t - r.timestamps[..., None])
                              <= self.interval)
            out_keys = jnp.broadcast_to(r.keys[..., None], match.shape)
            out_vals = cand_v + r.values[..., None]
            out_ts = jnp.broadcast_to(r.timestamps[..., None], match.shape)
            # Per-step compaction in (right-slot, ring-slot) order.
            fm = match.reshape(G, B2 * w)
            pos = jnp.cumsum(fm.astype(jnp.int32), axis=1) - 1
            keep2 = fm & (pos < cap)
            dst = jnp.where(keep2, pos, cap)
            gidx = jnp.arange(G, dtype=jnp.int32)[:, None]

            def comp(src, dt):
                return jnp.zeros((G, cap + 1), dt).at[gidx, dst].set(
                    jnp.where(keep2, src.reshape(G, B2 * w),
                              jnp.zeros((), dt)),
                    mode="drop")[:, :cap]
            out = zero_invalid(RecordBatch(
                comp(out_keys, jnp.int32), comp(out_vals, jnp.int32),
                comp(out_ts, jnp.int32), comp(keep2, jnp.bool_)))
            # End-of-group ring: slot j <- latest group arrival with
            # rank ≡ (j - cursor) mod w, else the carried slot.
            tmod_k = (js[None, :] - cur[:, None]) % w        # [nk, w]
            tot = total[:, None]
            rck = tot - 1 - ((tot - 1 - tmod_k) % w)
            use_k = (tot > 0) & (rck >= 0)
            rck_s = jnp.clip(rck, 0, n - 1)
            kidx = ks[:, None]
            lv2 = jnp.where(use_k, Tv[kidx, rck_s], lv)
            lt2 = jnp.where(use_k, Tt[kidx, rck_s], lt)
            lm2 = lm | use_k
            return lv2, lt2, lm2, cur + total, out

        def group_step(carry, xs):
            lv, lt, lm, cur = carry
            gl, gr = xs                  # [G, P, *]
            lv, lt, lm, cur, out = jax.vmap(
                one, in_axes=(0, 0, 0, 0, 1, 1), out_axes=(
                    0, 0, 0, 0, 1))(lv, lt, lm, cur, gl, gr)
            return (lv, lt, lm, cur), out

        regroup = lambda t: jax.tree_util.tree_map(
            lambda x: x.reshape((K // G, G) + x.shape[1:]), t)
        (lv, lt, lm, cur), outs = jax.lax.scan(
            group_step,
            (state["lv"], state["lt"], state["lm"], state["cursor"]),
            (regroup(left), regroup(right)))
        out = jax.tree_util.tree_map(
            lambda x: x.reshape((K,) + x.shape[2:]), outs)
        return {"lv": lv, "lt": lt, "lm": lm, "cursor": cur}, out


@dataclasses.dataclass
class TransactionalSinkOperator(Operator):
    """Exactly-once sink (TwoPhaseCommitSinkFunction analog): emissions
    flow to the host-side runtime.txn.TransactionLog as per-epoch pending
    transactions, committed only when the epoch's checkpoint completes.
    Device-side it is a pass-through counter like SinkOperator."""

    def init_state(self, parallelism: int):
        return {"emitted": jnp.zeros((parallelism,), jnp.int32)}

    def process(self, state, batch, ctx):
        return ({"emitted": state["emitted"] + batch.count()},
                zero_invalid(batch))

    def process_block(self, state, batches, bctx):
        out = zero_invalid(batches)
        return ({"emitted": state["emitted"] + out.count().sum(axis=0)},
                out)


@dataclasses.dataclass
class HostFeedSource(Operator):
    """Source fed by the host boundary (the Kafka/socket-source analog).

    The executor passes the pulled batch in as this vertex's input batch;
    the operator stamps timestamps and passes it through. Offset state
    makes the checkpoint carry the feed position (the Kafka-offset-in-
    checkpoint pattern); replay re-reads the same records from the
    rewindable reader (reference: sources restore offsets and the causal
    log pins the per-buffer cut counts)."""

    batch_size: int

    @property
    def out_capacity(self):  # type: ignore[override]
        return self.batch_size

    def init_state(self, parallelism: int):
        return {"offset": jnp.zeros((parallelism,), jnp.int32)}

    def process(self, state, batch, ctx):
        out = zero_invalid(batch._replace(
            timestamps=jnp.where(batch.valid, ctx.time, 0)))
        return {"offset": state["offset"] + out.count()}, out

    def process_block(self, state, batches, bctx):
        out = zero_invalid(batches._replace(
            timestamps=jnp.where(batches.valid, bctx.times[:, None, None], 0)))
        return ({"offset": state["offset"] + out.count().sum(axis=0)}, out)


@dataclasses.dataclass
class SinkOperator(Operator):
    """Terminal operator: passes its input through as the job's visible
    output (the executor surfaces it to the host) and counts emissions
    (DiscardingSink/collect-sink analog)."""

    def init_state(self, parallelism: int):
        return {"emitted": jnp.zeros((parallelism,), jnp.int32)}

    def process(self, state, batch, ctx):
        return ({"emitted": state["emitted"] + batch.count()},
                zero_invalid(batch))

    def process_block(self, state, batches, bctx):
        out = zero_invalid(batches)
        return ({"emitted": state["emitted"] + out.count().sum(axis=0)}, out)
