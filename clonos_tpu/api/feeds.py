"""Feed readers: the external-ingestion boundary (connector analog).

Capability analog of the reference's source connectors
(flink-connectors — Kafka FlinkKafkaConsumer et al.): a *rewindable,
partitioned* record feed. The two operations mirror the exactly-once
contract the Kafka consumer gives Flink:

- ``pull(subtask, max_n)``        — live path: take up to ``max_n`` records
                                    from the subtask's partition cursor.
- ``read_at(subtask, offset, n)`` — recovery path: re-read an exact range
                                    (offsets restored from the checkpointed
                                    HostFeedSource state; per-step counts
                                    pinned by BUFFER_BUILT determinants).

Readers return ``(keys, values)`` int lists. Timestamps are stamped by the
operator from causal time, so feeds stay replay-exact.
"""

from __future__ import annotations

import socket
import threading
from typing import List, Sequence, Tuple


class FeedReader:
    def pull(self, subtask: int, max_n: int) -> Tuple[List[int], List[int]]:
        raise NotImplementedError

    def read_at(self, subtask: int, offset: int, n: int
                ) -> Tuple[List[int], List[int]]:
        raise NotImplementedError


class ListFeedReader(FeedReader):
    """In-memory partitioned feed (tests / bounded replays). Retains all
    records, so any range can be re-read (a Kafka topic with infinite
    retention)."""

    def __init__(self, partitions: Sequence[Sequence[Tuple[int, int]]],
                 records_per_pull: int = 1 << 30):
        self._parts = [list(p) for p in partitions]
        self._cursor = [0] * len(self._parts)
        self.records_per_pull = records_per_pull

    def pull(self, subtask: int, max_n: int):
        lo = self._cursor[subtask]
        n = min(max_n, self.records_per_pull,
                len(self._parts[subtask]) - lo)
        self._cursor[subtask] = lo + n
        chunk = self._parts[subtask][lo: lo + n]
        return [k for k, _ in chunk], [v for _, v in chunk]

    def read_at(self, subtask: int, offset: int, n: int):
        chunk = self._parts[subtask][offset: offset + n]
        if len(chunk) != n:
            raise ValueError(
                f"feed partition {subtask} cannot re-serve [{offset}, "
                f"{offset + n}): retention too short")
        return [k for k, _ in chunk], [v for _, v in chunk]


class SocketFeedReader(FeedReader):
    """Line-based TCP ingestion (the SocketWindowWordCount front door,
    reference flink-examples .../socket/SocketWindowWordCount.java). A
    background thread drains the socket into an in-memory retained buffer
    per subtask (single-partition: subtask 0), so the rewindable contract
    still holds for ranges within retention.

    Lines are ``key[:value]`` integer pairs; value defaults to 1.
    """

    def __init__(self, host: str, port: int, num_subtasks: int = 1):
        self._buf: List[List[Tuple[int, int]]] = [
            [] for _ in range(num_subtasks)]
        self._cursor = [0] * num_subtasks
        self._lock = threading.Lock()
        self._sock = socket.create_connection((host, port))
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        f = self._sock.makefile("r")
        i = 0
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                if ":" in line:
                    k, v = line.split(":", 1)
                    rec = (int(k), int(v))
                else:
                    rec = (int(line), 1)
            except ValueError:
                continue
            with self._lock:
                self._buf[i % len(self._buf)].append(rec)
            i += 1

    def pull(self, subtask: int, max_n: int):
        with self._lock:
            lo = self._cursor[subtask]
            chunk = self._buf[subtask][lo: lo + max_n]
            self._cursor[subtask] = lo + len(chunk)
        return [k for k, _ in chunk], [v for _, v in chunk]

    def read_at(self, subtask: int, offset: int, n: int):
        with self._lock:
            chunk = self._buf[subtask][offset: offset + n]
        if len(chunk) != n:
            raise ValueError(
                f"socket feed cannot re-serve [{offset}, {offset + n})")
        return [k for k, _ in chunk], [v for _, v in chunk]
