"""Feed readers: the external-ingestion boundary (connector analog).

Capability analog of the reference's source connectors
(flink-connectors — Kafka FlinkKafkaConsumer et al.): a *rewindable,
partitioned, bounded-retention* record feed. The operations mirror the
exactly-once contract the Kafka consumer gives Flink:

- ``pull(subtask, max_n)``        — live path: take up to ``max_n`` records
                                    from the subtask's partition cursor.
- ``pull_block(subtask, b, k)``   — live hot path: k steps' worth of
                                    pulls in one call, returned as dense
                                    [k, b] arrays (the executor's block
                                    program ingests whole blocks; a
                                    per-step per-subtask Python loop was
                                    the ingestion throughput cap).
- ``read_at(subtask, offset, n)`` — recovery path: re-read an exact range
                                    (offsets restored from the checkpointed
                                    HostFeedSource state; per-step counts
                                    pinned by BUFFER_BUILT determinants).
- ``notify_checkpoint_complete``  — durability hook: offsets up to the
                                    completed checkpoint are *committed*
                                    (FlinkKafkaConsumerBase
                                    .notifyCheckpointComplete pattern);
                                    the reader may release retention
                                    below them, bounding memory.

Retention is bounded, as in a real broker: each partition tracks a
``base`` offset below which records are gone. Reading below base raises
:class:`RetentionExpiredError` — loudly, at the exact offset — never a
silent wrong answer. Recovery re-reads only from the latest *completed*
checkpoint's offsets, so committing retention at checkpoint completion
is always safe; an over-aggressive ``retention`` cap (records dropped
before any checkpoint committed them) surfaces as this error at
recovery time, exactly like a Kafka consumer falling behind a topic's
retention window.

Readers return ``(keys, values)`` int lists (or [k, b] int32 arrays from
``pull_block``). Timestamps are stamped by the operator from causal
time, so feeds stay replay-exact.
"""

from __future__ import annotations

import socket
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np


class RetentionExpiredError(ValueError):
    """A re-read landed below a partition's retention floor: the records
    are gone (dropped by the retention cap before a checkpoint committed
    past them). The reference hits the identical wall when a recovering
    Kafka source's restored offset has aged out of the topic."""


class FeedReader:
    def pull(self, subtask: int, max_n: int) -> Tuple[List[int], List[int]]:
        raise NotImplementedError

    def read_at(self, subtask: int, offset: int, n: int
                ) -> Tuple[List[int], List[int]]:
        raise NotImplementedError

    def pull_block(self, subtask: int, batch: int, k: int
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """k consecutive pulls as dense arrays: (keys [k, batch] int32,
        values [k, batch] int32, counts [k] int32). Default: loop over
        :meth:`pull`; array-backed readers override with a slice."""
        ks = np.zeros((k, batch), np.int32)
        vs = np.zeros((k, batch), np.int32)
        counts = np.zeros((k,), np.int32)
        for i in range(k):
            kk, vv = self.pull(subtask, batch)
            n = len(kk)
            ks[i, :n], vs[i, :n], counts[i] = kk, vv, n
        return ks, vs, counts

    def notify_checkpoint_complete(self, offsets: Sequence[int]) -> None:
        """Offsets[subtask] are durably checkpointed: recovery will never
        re-read below them. Default: no-op (infinite retention)."""


def _floor_check(base: int, subtask: int, offset: int) -> None:
    if offset < base:
        raise RetentionExpiredError(
            f"partition {subtask}: offset {offset} is below the "
            f"retention floor {base} — records expired before a "
            f"checkpoint committed past them")


class _RetainedPartitions:
    """Shared bounded-retention core: per-partition record storage with a
    base offset; all offsets are absolute (monotone across truncation)."""

    def __init__(self, num_parts: int, retention: Optional[int]):
        self._parts: List[List[Tuple[int, int]]] = [
            [] for _ in range(num_parts)]
        self._base = [0] * num_parts
        self._cursor = [0] * num_parts
        self.retention = retention

    def _check_floor(self, subtask: int, offset: int) -> None:
        _floor_check(self._base[subtask], subtask, offset)

    def _slice(self, subtask: int, offset: int, n: int):
        self._check_floor(subtask, offset)
        lo = offset - self._base[subtask]
        chunk = self._parts[subtask][lo: lo + n]
        if len(chunk) != n:
            raise ValueError(
                f"feed partition {subtask} cannot serve [{offset}, "
                f"{offset + n}): only {len(chunk)} records available")
        return chunk

    def truncate_below(self, subtask: int, offset: int) -> None:
        drop = offset - self._base[subtask]
        if drop > 0:
            del self._parts[subtask][:drop]
            self._base[subtask] = offset

    def _enforce_retention(self, subtask: int) -> None:
        # Kafka-style size bound: only the newest `retention` records per
        # partition survive, consumed or not.
        if self.retention is None:
            return
        excess = len(self._parts[subtask]) - self.retention
        if excess > 0:
            self.truncate_below(subtask, self._base[subtask] + excess)

    def commit(self, offsets: Sequence[int]) -> None:
        for s, off in enumerate(offsets):
            # Never raise the floor above consumption: the committed
            # offset bounds replays, the cursor bounds live progress.
            self.truncate_below(s, min(int(off), self._cursor[s]))


class ListFeedReader(FeedReader):
    """In-memory partitioned feed (tests / bounded replays), stored as
    dense [N, 2] int32 arrays for the block fast path. The preloaded
    list models a stream arriving over time, so a finite ``retention``
    bounds records kept *behind the consumption cursor* (replayable
    history), never unconsumed future records; ``retention=None`` keeps
    everything (a topic with infinite retention)."""

    def __init__(self, partitions: Sequence[Sequence[Tuple[int, int]]],
                 records_per_pull: int = 1 << 30,
                 retention: Optional[int] = None):
        self._np = [np.asarray(list(p), np.int32).reshape(-1, 2)
                    for p in partitions]
        self._base = [0] * len(self._np)
        self._cursor = [0] * len(self._np)
        self.retention = retention
        self.records_per_pull = records_per_pull
        # notify_checkpoint_complete can arrive from the coordinator's
        # async writer thread while the executor pulls on the main
        # thread; trims and reads must not interleave.
        self._lock = threading.Lock()

    def _check_floor(self, subtask: int, offset: int) -> None:
        _floor_check(self._base[subtask], subtask, offset)

    def _trim_to(self, subtask: int, floor: int) -> None:
        drop = floor - self._base[subtask]
        if drop > 0:
            self._np[subtask] = self._np[subtask][drop:]
            self._base[subtask] = floor

    def _trim_retention(self, subtask: int) -> None:
        if self.retention is not None:
            self._trim_to(subtask,
                          self._cursor[subtask] - self.retention)

    def _advance(self, subtask: int, n_max: int) -> np.ndarray:
        with self._lock:
            lo = self._cursor[subtask]
            self._check_floor(subtask, lo)
            rel = lo - self._base[subtask]
            chunk = self._np[subtask][rel: rel + n_max]
            self._cursor[subtask] = lo + len(chunk)
            self._trim_retention(subtask)
            return chunk

    def pull(self, subtask: int, max_n: int):
        chunk = self._advance(subtask,
                              min(max_n, self.records_per_pull))
        return chunk[:, 0].tolist(), chunk[:, 1].tolist()

    def pull_block(self, subtask: int, batch: int, k: int):
        per = min(batch, self.records_per_pull)
        flat = self._advance(subtask, k * per)
        take = len(flat)
        ks = np.zeros((k, batch), np.int32)
        vs = np.zeros((k, batch), np.int32)
        counts = np.zeros((k,), np.int32)
        full = take // per
        counts[:full] = per
        if full:
            blk = flat[: full * per].reshape(full, per, 2)
            ks[:full, :per] = blk[:, :, 0]
            vs[:full, :per] = blk[:, :, 1]
        tail = take - full * per
        if tail and full < k:
            counts[full] = tail
            ks[full, :tail] = flat[full * per:, 0]
            vs[full, :tail] = flat[full * per:, 1]
        return ks, vs, counts

    def read_at(self, subtask: int, offset: int, n: int):
        with self._lock:
            self._check_floor(subtask, offset)
            rel = offset - self._base[subtask]
            chunk = self._np[subtask][rel: rel + n]
        if len(chunk) != n:
            raise ValueError(
                f"feed partition {subtask} cannot re-serve [{offset}, "
                f"{offset + n}): retention too short")
        return chunk[:, 0].tolist(), chunk[:, 1].tolist()

    def notify_checkpoint_complete(self, offsets: Sequence[int]) -> None:
        with self._lock:
            for s, off in enumerate(offsets):
                # Never drop past what's been consumed: the committed
                # offset bounds replays, the cursor bounds live progress.
                self._trim_to(s, min(int(off), self._cursor[s]))


class SocketFeedReader(FeedReader):
    """Line-based TCP ingestion (the SocketWindowWordCount front door,
    reference flink-examples .../socket/SocketWindowWordCount.java). A
    background thread drains the socket into a bounded retained buffer
    per subtask, so the rewindable contract holds for ranges within
    retention and memory stays bounded for long-running feeds
    (``retention`` records per partition; committed offsets release
    earlier ones at every completed checkpoint).

    Lines are ``key[:value]`` integer pairs; value defaults to 1.
    """

    def __init__(self, host: str, port: int, num_subtasks: int = 1,
                 retention: Optional[int] = 1 << 20):
        self._r = _RetainedPartitions(num_subtasks, retention)
        #: records dropped by retention before the consumer reached them
        #: (the consumer fell behind the window; live pulls skip forward —
        #: Kafka's auto.offset.reset=earliest — but the loss is counted,
        #: never silent).
        self.records_lost = [0] * num_subtasks
        self._lock = threading.Lock()
        self._sock = socket.create_connection((host, port))
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        f = self._sock.makefile("r")
        i = 0
        nparts = len(self._r._parts)
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                if ":" in line:
                    k, v = line.split(":", 1)
                    rec = (int(k), int(v))
                else:
                    rec = (int(line), 1)
            except ValueError:
                continue
            s = i % nparts
            with self._lock:
                self._r._parts[s].append(rec)
                self._r._enforce_retention(s)
            i += 1

    def pull(self, subtask: int, max_n: int):
        with self._lock:
            r = self._r
            lo = r._cursor[subtask]
            if lo < r._base[subtask]:
                # Fell behind the retention window: the records are gone.
                # Resume at the earliest retained offset and account for
                # the gap (recovery re-reads via read_at still fail loud).
                self.records_lost[subtask] += r._base[subtask] - lo
                lo = r._base[subtask]
            avail = r._base[subtask] + len(r._parts[subtask]) - lo
            n = min(max_n, avail)
            chunk = r._slice(subtask, lo, n)
            r._cursor[subtask] = lo + n
        return [k for k, _ in chunk], [v for _, v in chunk]

    def read_at(self, subtask: int, offset: int, n: int):
        with self._lock:
            chunk = self._r._slice(subtask, offset, n)
        return [k for k, _ in chunk], [v for _, v in chunk]

    def notify_checkpoint_complete(self, offsets: Sequence[int]) -> None:
        with self._lock:
            self._r.commit(offsets)
