"""Record batches: the unit of dataflow on TPU.

The reference moves one serialized record at a time through netty buffers
(flink-runtime .../io/network/api/writer/RecordWriter.java:60-101,
serialization in SpanningRecordSerializer). A record-at-a-time design wastes
a TPU; here the unit is a **fixed-capacity batch** — a struct-of-arrays
pytree with a validity mask, so every operator is a dense vectorized op and
XLA sees static shapes.

A record is ``(key: int32, value: int32, timestamp: int32)``. This covers
the reference's benchmark workloads (wordcount, keyed windows, joins); rich
payloads ride in ``value`` as indices into application-side tables.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class RecordBatch(NamedTuple):
    """Fixed-capacity struct-of-arrays batch. Leading dims are arbitrary
    (e.g. ``[P, B]`` for a vertex with parallelism P); the mask marks live
    rows — padding rows must be zeroed so replay comparisons are exact."""

    keys: jnp.ndarray       # int32[..., B]
    values: jnp.ndarray     # int32[..., B]
    timestamps: jnp.ndarray # int32[..., B]
    valid: jnp.ndarray      # bool[..., B]

    @property
    def capacity(self) -> int:
        return self.keys.shape[-1]

    def count(self) -> jnp.ndarray:
        """Live records per leading index (int32[...])."""
        return jnp.sum(self.valid, axis=-1).astype(jnp.int32)


def empty(shape) -> RecordBatch:
    # Distinct buffers per field: sharing one zeros array across leaves
    # breaks buffer donation (the executor donates the carry, and XLA
    # rejects donating the same buffer twice).
    shape = tuple(shape) if not isinstance(shape, int) else (shape,)
    return RecordBatch(jnp.zeros(shape, jnp.int32),
                       jnp.zeros(shape, jnp.int32),
                       jnp.zeros(shape, jnp.int32),
                       jnp.zeros(shape, jnp.bool_))


def make(keys, values=None, timestamps=None, capacity=None) -> RecordBatch:
    """Host-side constructor from numpy/lists, padded to ``capacity``."""
    keys = np.asarray(keys, np.int32)
    values = np.asarray(values if values is not None else np.ones_like(keys), np.int32)
    timestamps = np.asarray(
        timestamps if timestamps is not None else np.zeros_like(keys), np.int32)
    n = keys.shape[-1]
    cap = capacity or n
    if n > cap:
        raise ValueError(f"{n} records exceed capacity {cap}")
    pad = [(0, 0)] * (keys.ndim - 1) + [(0, cap - n)]
    valid = np.pad(np.ones(keys.shape, bool), pad)
    return RecordBatch(
        jnp.asarray(np.pad(keys, pad)), jnp.asarray(np.pad(values, pad)),
        jnp.asarray(np.pad(timestamps, pad)), jnp.asarray(valid))


def zero_invalid(batch: RecordBatch) -> RecordBatch:
    """Force padding rows to zero — the canonical form all operators must
    emit so that bit-identical replay comparison is meaningful."""
    m = batch.valid
    return RecordBatch(
        jnp.where(m, batch.keys, 0), jnp.where(m, batch.values, 0),
        jnp.where(m, batch.timestamps, 0), m)


def to_numpy(batch: RecordBatch):
    """Host view: list of (key, value, ts) tuples for the valid rows of a
    rank-1 batch (tests / sinks)."""
    k = np.asarray(batch.keys).reshape(-1)
    v = np.asarray(batch.values).reshape(-1)
    t = np.asarray(batch.timestamps).reshape(-1)
    m = np.asarray(batch.valid).reshape(-1)
    return [(int(k[i]), int(v[i]), int(t[i])) for i in range(m.size) if m[i]]
