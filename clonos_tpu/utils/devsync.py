"""Reliable device-completion sync.

``jax.block_until_ready`` can return before work completes on tunneled /
experimental backends, so timing code must force a real device→host read.
This is the single shared copy of that workaround (bench.py and the
tools/ profilers import it).
"""

from __future__ import annotations

import numpy as np


def device_sync(tree) -> None:
    """Block until ``tree``'s device work is actually finished by reading
    one element of one leaf back to the host."""
    import jax
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "shape")]
    if not leaves:
        return
    x = leaves[0]
    np.asarray(x.ravel()[0] if getattr(x, "ndim", 0) else x)
