"""Persistent XLA compilation cache (jax_compilation_cache_dir).

The failure path is prewarm-compiled at job start; with this cache a
RESTARTED job pays near-zero for those compiles (the reference's standby
deploy analog survives process restarts). Safe to share across backends:
JAX keys entries by HLO + compile-options hash.

Mesh-sharded programs get a cache *namespace* of their own: JAX's entry
key covers HLO + compile options, but a program lowered under an
8-device mesh and its single-device twin can share module text while
their executables are incompatible across partitioner versions — so
:func:`enable_compile_cache` accepts the mesh + PartitionSpec pytree
and keys a per-sharding subdirectory from their fingerprints. Unsharded
and sharded runs therefore never collide in the persistent cache.

The standby/bootstrap path wires through here too
(``ClusterRunner(compile_cache_dir=...)`` /
``ClusterRunner.bootstrap_standby(compile_cache_dir=...)``): the
first-step executable :func:`aot_lower_first_step` produces at prewarm
persists across a process restart, so a rebooted standby's in-bootstrap
AOT warm is a persistent-cache HIT instead of the full
``finalize.first-step-recompile`` XLA compile.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional


def sharding_cache_key(mesh: Optional[Any] = None,
                       specs: Optional[Any] = None) -> str:
    """Cache-namespace token for a (mesh, PartitionSpec pytree) pair.
    ``None``/``None`` (the single-device program) gets its own stable
    token, so turning sharding on or off switches namespaces."""
    from clonos_tpu.parallel.distributed import (mesh_fingerprint,
                                                 spec_fingerprint)
    mk = mesh_fingerprint(mesh)
    sk = spec_fingerprint(specs) if specs is not None else "nospec"
    return f"{mk}-{sk}"


def enable_compile_cache(cache_dir: str, mesh: Optional[Any] = None,
                         specs: Optional[Any] = None) -> str:
    """Point JAX's persistent compile cache at ``cache_dir`` — suffixed
    with :func:`sharding_cache_key` when a mesh (and optionally the
    carry's PartitionSpec pytree) is given. Returns the directory used."""
    import jax
    if mesh is not None or specs is not None:
        cache_dir = os.path.join(cache_dir,
                                 sharding_cache_key(mesh, specs))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Only compiles past this wall are persisted (dodges churn from
    # trivial jits). CLONOS_COMPILE_CACHE_MIN_S=0 forces everything in
    # — small jobs whose block compiles beat 0.5 s still want their
    # first-step executable to survive a restart.
    min_s = float(os.environ.get("CLONOS_COMPILE_CACHE_MIN_S", "0.5"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", min_s)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:                              # pragma: no cover
        pass  # knob name varies across jax versions
    return cache_dir


def aot_lower_first_step(executor, metric_group: Optional[Any] = None
                         ) -> Optional[Any]:
    """Ahead-of-time lower + compile the standby's FIRST-STEP program —
    the block program a rehydrating standby dispatches before anything
    else — so its executable is in the persistent cache (and XLA's
    in-process cache) before any failure happens. BENCH_r05 puts
    first-step-recompile inside the dominant ~448 ms finalize tail; a
    cache hit removes it.

    Lowering uses the executor's live carry avals + shardings (no
    execution, no donation — ``lower`` only traces). Returns the
    compiled executable, or None when lowering is unsupported on this
    backend/version (callers treat AOT warmup as best-effort) — the
    fallback is NOT silent: it emits a ``recovery.aot-lower-failed``
    trace instant and, when ``metric_group`` is given, bumps the
    counter of the same name, so a standby that will pay the cold
    recompile at failover is visible in ``clonos_tpu top`` now."""
    from clonos_tpu.obs.trace import get_tracer
    t0 = time.monotonic()
    try:
        carry = executor.carry      # one read: stable vs concurrent swap
        exe = executor._jit_block.lower(
            carry, executor.first_step_inputs()).compile()
        get_tracer().complete("recovery.aot-lower",
                              time.monotonic() - t0)
        return exe
    except Exception as err:
        get_tracer().event("recovery.aot-lower-failed",
                           error=repr(err)[:200])
        if metric_group is not None:
            metric_group.counter("recovery.aot-lower-failed").inc()
        return None
