"""Persistent XLA compilation cache (jax_compilation_cache_dir).

The failure path is prewarm-compiled at job start; with this cache a
RESTARTED job pays near-zero for those compiles (the reference's standby
deploy analog survives process restarts). Safe to share across backends:
JAX keys entries by HLO + compile-options hash.

Mesh-sharded programs get a cache *namespace* of their own: JAX's entry
key covers HLO + compile options, but a program lowered under an
8-device mesh and its single-device twin can share module text while
their executables are incompatible across partitioner versions — so
:func:`enable_compile_cache` accepts the mesh + PartitionSpec pytree
and keys a per-sharding subdirectory from their fingerprints. Unsharded
and sharded runs therefore never collide in the persistent cache.
"""

from __future__ import annotations

import os
from typing import Any, Optional


def sharding_cache_key(mesh: Optional[Any] = None,
                       specs: Optional[Any] = None) -> str:
    """Cache-namespace token for a (mesh, PartitionSpec pytree) pair.
    ``None``/``None`` (the single-device program) gets its own stable
    token, so turning sharding on or off switches namespaces."""
    from clonos_tpu.parallel.distributed import (mesh_fingerprint,
                                                 spec_fingerprint)
    mk = mesh_fingerprint(mesh)
    sk = spec_fingerprint(specs) if specs is not None else "nospec"
    return f"{mk}-{sk}"


def enable_compile_cache(cache_dir: str, mesh: Optional[Any] = None,
                         specs: Optional[Any] = None) -> str:
    """Point JAX's persistent compile cache at ``cache_dir`` — suffixed
    with :func:`sharding_cache_key` when a mesh (and optionally the
    carry's PartitionSpec pytree) is given. Returns the directory used."""
    import jax
    if mesh is not None or specs is not None:
        cache_dir = os.path.join(cache_dir,
                                 sharding_cache_key(mesh, specs))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:                              # pragma: no cover
        pass  # knob name varies across jax versions
    return cache_dir


def aot_lower_first_step(executor) -> Optional[Any]:
    """Ahead-of-time lower + compile the standby's FIRST-STEP program —
    the sharded block program a rehydrating standby dispatches before
    anything else — so its executable is in the persistent cache (and
    XLA's in-process cache) before any failure happens. BENCH_r05 puts
    first-step-recompile inside the dominant ~448 ms finalize tail; a
    cache hit removes it.

    Lowering uses the executor's live carry avals + shardings (no
    execution, no donation — ``lower`` only traces). Returns the
    compiled executable, or None when lowering is unsupported on this
    backend/version (callers treat AOT warmup as best-effort)."""
    import jax.numpy as jnp

    from clonos_tpu.runtime.executor import BlockInputs
    try:
        k = executor.block_steps
        bi = BlockInputs(times=jnp.zeros((k,), jnp.int32),
                         rng_bits=jnp.zeros((k,), jnp.int32),
                         epoch=jnp.zeros((), jnp.int32),
                         step0=jnp.zeros((), jnp.int32), feeds=())
        return executor._jit_block.lower(executor.carry, bi).compile()
    except Exception:                              # pragma: no cover
        return None
