"""Persistent XLA compilation cache (jax_compilation_cache_dir).

The failure path is prewarm-compiled at job start; with this cache a
RESTARTED job pays near-zero for those compiles (the reference's standby
deploy analog survives process restarts). Safe to share across backends:
JAX keys entries by HLO + compile-options hash.
"""

from __future__ import annotations

import os


def enable_compile_cache(cache_dir: str) -> None:
    import jax
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:                              # pragma: no cover
        pass  # knob name varies across jax versions
