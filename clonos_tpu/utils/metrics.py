"""Metrics: registry, metric types, scopes, reporters.

Capability parity with the reference's metrics system
(flink-runtime .../metrics/MetricRegistryImpl.java:66, metric groups with
job/task/operator scopes, pluggable reporters in flink-metrics-{jmx,
prometheus,datadog,graphite,statsd,slf4j,dropwizard}) — scoped to what a
single-process-control-plane framework needs: Counter/Gauge/Meter/Histogram,
hierarchical scopes, and two reporters (logging, JSON-lines file; the
prometheus-style text dump doubles as a scrape endpoint payload).

Also carries the Clonos determinant-buffer watchdog analog
(JobCausalLogImpl.java:268-298: a thread logging determinant pool occupancy
every second) as :class:`LogOccupancyWatchdog` over the device log sizes.
"""

from __future__ import annotations

import collections
import json
import re
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional

import numpy as np

# Prometheus exposition hygiene: metric names must match
# [a-zA-Z_:][a-zA-Z0-9_:]* and label values escape backslash, quote and
# newline (exposition format v0.0.4).
_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    metric = _PROM_NAME_RE.sub("_", name)
    if metric and metric[0].isdigit():
        metric = "_" + metric
    return metric or "_"


def _prom_label_escape(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class Counter:
    def __init__(self):
        self._v = 0

    def inc(self, n: int = 1) -> None:
        self._v += n

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    """Wraps a supplier (evaluated at report time)."""

    def __init__(self, fn: Callable[[], Any]):
        self._fn = fn

    @property
    def value(self):
        return self._fn()


class Meter:
    """Rate of events/sec over a sliding window."""

    def __init__(self, window_s: float = 10.0, clock=time.monotonic):
        self._events: Deque[tuple] = collections.deque()
        self._window = window_s
        self._clock = clock

    def mark(self, n: int = 1) -> None:
        now = self._clock()
        self._events.append((now, n))
        cut = now - self._window
        while self._events and self._events[0][0] < cut:
            self._events.popleft()

    @property
    def rate(self) -> float:
        now = self._clock()
        cut = now - self._window
        total = sum(n for t, n in self._events if t >= cut)
        return total / self._window


class Histogram:
    def __init__(self, max_samples: int = 1024):
        # deque(maxlen=...) evicts the oldest sample in O(1); the old
        # list.pop(0) made every update past capacity O(max_samples)
        self._buf: Deque[float] = collections.deque(maxlen=max_samples)

    def update(self, v: float) -> None:
        self._buf.append(v)

    def quantile(self, q: float) -> float:
        if not self._buf:
            return 0.0
        return float(np.quantile(np.asarray(self._buf), q))

    @property
    def count(self) -> int:
        return len(self._buf)

    @property
    def mean(self) -> float:
        return float(np.mean(self._buf)) if self._buf else 0.0


class MetricGroup:
    """Hierarchical scope (job -> task -> operator naming)."""

    def __init__(self, registry: "MetricRegistry", scope: str):
        self._registry = registry
        self.scope = scope

    def counter(self, name: str) -> Counter:
        return self._registry._register(f"{self.scope}.{name}", Counter())

    def gauge(self, name: str, fn: Callable[[], Any]) -> Gauge:
        return self._registry._register(f"{self.scope}.{name}", Gauge(fn))

    def meter(self, name: str, window_s: float = 10.0) -> Meter:
        return self._registry._register(f"{self.scope}.{name}",
                                        Meter(window_s))

    def histogram(self, name: str) -> Histogram:
        return self._registry._register(f"{self.scope}.{name}", Histogram())

    def remove(self, name: str) -> bool:
        return self._registry.unregister(f"{self.scope}.{name}")

    def add_group(self, name: str) -> "MetricGroup":
        return MetricGroup(self._registry, f"{self.scope}.{name}")


class MetricRegistry:
    """Root registry (MetricRegistryImpl analog)."""

    def __init__(self):
        self._metrics: Dict[str, Any] = {}
        self._reporters: List["Reporter"] = []
        self._lock = threading.Lock()

    def group(self, scope: str) -> MetricGroup:
        return MetricGroup(self, scope)

    def _register(self, full_name: str, metric):
        with self._lock:
            existing = self._metrics.get(full_name)
            if existing is not None:
                return existing
            self._metrics[full_name] = metric
            return metric

    def unregister(self, full_name: str) -> bool:
        """Drop a metric so its name can be re-registered fresh.
        ``_register`` dedupes by full name and returns the EXISTING
        metric — a dynamically retired component (e.g. a dropped read
        replica) must unregister, or a later same-named registration
        silently keeps the dead closure."""
        with self._lock:
            return self._metrics.pop(full_name, None) is not None

    def add_reporter(self, reporter: "Reporter") -> None:
        self._reporters.append(reporter)

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if isinstance(m, Counter):
                out[name] = m.value
            elif isinstance(m, Gauge):
                try:
                    out[name] = m.value
                except Exception as e:  # supplier died; report the fact
                    out[name] = f"<gauge error: {e}>"
            elif isinstance(m, Meter):
                out[name] = round(m.rate, 3)
            elif isinstance(m, Histogram):
                out[name] = {"count": m.count, "mean": round(m.mean, 3),
                             "p50": round(m.quantile(0.5), 3),
                             "p99": round(m.quantile(0.99), 3)}
        return out

    def report(self) -> None:
        snap = self.snapshot()
        for r in self._reporters:
            r.report(snap)

    def _prometheus_type(self, name: str, v: Any) -> str:
        """Exposition TYPE for one snapshot entry: registered metrics
        map by class; merged extras (worker heartbeat snapshots) are
        inferred from the value shape."""
        with self._lock:
            m = self._metrics.get(name)
        if isinstance(m, Counter):
            return "counter"
        if isinstance(m, Histogram):
            return "summary"
        if isinstance(m, (Gauge, Meter)):
            return "gauge"
        if isinstance(v, dict):
            return "summary" if {"count", "mean"} <= set(v) else "gauge"
        return "gauge"

    def prometheus_text(self, snapshot: Optional[Dict[str, Any]] = None
                        ) -> str:
        """Prometheus exposition-format (v0.0.4) dump with ``# HELP`` /
        ``# TYPE`` headers (pass a pre-merged ``snapshot`` to include
        e.g. cluster-wide values). Names are sanitized to the exposition
        charset; histogram snapshots flatten to ``<name>_{count,mean,
        p50,p99}`` sample lines; string values (e.g. gauge-supplier
        errors) render as info-style samples with the text in an escaped
        ``value`` label rather than being dropped."""
        lines = []
        if snapshot is None:
            snapshot = self.snapshot()
        for name, v in sorted(snapshot.items()):
            metric = _prom_name(name)
            lines.append(f"# HELP {metric} source metric {name}")
            lines.append(
                f"# TYPE {metric} {self._prometheus_type(name, v)}")
            if isinstance(v, bool):
                lines.append(f"{metric} {int(v)}")
            elif isinstance(v, (int, float)):
                lines.append(f"{metric} {v}")
            elif isinstance(v, dict):
                for k2, v2 in v.items():
                    if isinstance(v2, bool):
                        v2 = int(v2)
                    if isinstance(v2, (int, float)):
                        lines.append(f"{_prom_name(f'{metric}_{k2}')} {v2}")
                    else:
                        lines.append(
                            f'{_prom_name(f"{metric}_{k2}")}'
                            f'{{value="{_prom_label_escape(v2)}"}} 1')
            else:
                lines.append(
                    f'{metric}{{value="{_prom_label_escape(v)}"}} 1')
        return "\n".join(lines) + "\n"


class Reporter:
    def report(self, snapshot: Dict[str, Any]) -> None:
        raise NotImplementedError


class LoggingReporter(Reporter):
    def __init__(self, log_fn: Callable[[str], None] = print):
        self._log = log_fn

    def report(self, snapshot: Dict[str, Any]) -> None:
        self._log(json.dumps(snapshot, default=str))


class JsonLinesReporter(Reporter):
    """Appends one JSON object per report to a file (the scrape/ship
    boundary for external systems)."""

    # clonos: allow(wallclock): report timestamps for external scrapers
    def __init__(self, path: str, clock=time.time):
        self._path = path
        self._clock = clock
        self._file = None
        self._lock = threading.Lock()

    def report(self, snapshot: Dict[str, Any]) -> None:
        rec = {"ts": self._clock(), **snapshot}
        with self._lock:
            # one append-mode handle for the reporter's lifetime;
            # flush per record so readers (and crashes) see every line
            if self._file is None:
                self._file = open(self._path, "a")
            self._file.write(json.dumps(rec, default=str) + "\n")
            self._file.flush()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


class ReporterThread:
    """Periodic reporting driver (the registry's reporter scheduler)."""

    def __init__(self, registry: MetricRegistry, interval_s: float = 1.0):
        self._registry = registry
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self._registry.report()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        for r in self._registry._reporters:
            close = getattr(r, "close", None)
            if close is not None:
                close()


class LogOccupancyWatchdog:
    """Clonos determinant-buffer watchdog analog
    (JobCausalLogImpl.java:268-298): samples causal-log occupancy and warns
    as the ring approaches capacity."""

    def __init__(self, executor, group: MetricGroup,
                 warn_fraction: float = 0.8,
                 warn_fn: Callable[[str], None] = print):
        self._executor = executor
        self._warn_fraction = warn_fraction
        self._warn = warn_fn
        group.gauge("causal-log.max-occupancy", self.max_occupancy)
        group.gauge("causal-log.total-rows", self.total_rows)

    def max_occupancy(self) -> float:
        sizes = self._executor.log_sizes()
        cap = self._executor.compiled.log_capacity
        return float(sizes.max()) / cap if sizes.size else 0.0

    def total_rows(self) -> int:
        return int(self._executor.log_sizes().sum())

    def check(self) -> bool:
        occ = self.max_occupancy()
        if occ >= self._warn_fraction:
            self._warn(
                f"causal log occupancy {occ:.0%} >= {self._warn_fraction:.0%}"
                f" — checkpoint soon or determinants will be overwritten")
            return True
        return False


class MetricsEndpoint:
    """Serves the registry over HTTP (reference WebMonitorEndpoint /
    rest handlers, WebMonitorEndpoint.java:148 — scoped to the two
    surfaces a headless job needs): ``/metrics`` in Prometheus
    exposition format, ``/metrics.json`` as a JSON snapshot, and
    ``/trace`` as the tracer's flight-recorder ring rendered as Chrome
    trace JSON. Runs on a daemon thread; scrape-only (no job control),
    so it touches no device state.

    ``extra`` is a zero-arg callable returning additional name→value
    pairs merged into both metric views — the JobMaster passes its
    aggregated per-worker heartbeat snapshots here so one scrape covers
    the whole cluster. ``tracer`` (any object with ``records()``)
    backs ``/trace``; without one the path 404s. ``history`` (an
    ``obs.MetricsHistory``) backs ``/metrics/history.json?since=TS&
    last=N``; a history without a ``sample_fn`` samples this
    endpoint's merged view, and an unstarted one is started (and owned
    — ``close()`` stops it)."""

    def __init__(self, registry: MetricRegistry, host: str = "127.0.0.1",
                 port: int = 0,
                 extra: Optional[Callable[[], Dict[str, Any]]] = None,
                 tracer=None, history=None):
        import http.server
        import json as _json
        import threading
        import urllib.parse as _urlparse

        reg = registry

        def merged():
            snap = reg.snapshot()
            if extra is not None:
                try:
                    snap.update(extra())
                except Exception as e:
                    snap["extra-error"] = repr(e)
            if tracer is not None and getattr(tracer, "enabled", False):
                # ring-overflow visibility: nonzero means the in-memory
                # flight recorder (and /trace) is TRUNCATED
                snap["trace.dropped-records"] = getattr(
                    tracer, "dropped", 0)
            return snap

        self._history = history
        self._owns_history = False
        if history is not None:
            if history.sample_fn is None:
                history.sample_fn = merged
            if not history.started:
                history.start()
                self._owns_history = True

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                url = _urlparse.urlsplit(self.path)
                route = url.path.rstrip("/")
                if route == "/metrics":
                    body = reg.prometheus_text(merged()).encode()
                    ctype = "text/plain; version=0.0.4"
                elif route == "/metrics.json":
                    body = _json.dumps(merged(), default=str).encode()
                    ctype = "application/json"
                elif route == "/metrics/history.json" and \
                        history is not None:
                    q = _urlparse.parse_qs(url.query)

                    def _num(key, cast):
                        try:
                            return cast(q[key][0])
                        except (KeyError, IndexError, ValueError):
                            return None

                    body = _json.dumps(
                        {"samples": history.query(
                            since=_num("since", float),
                            last=_num("last", int))},
                        default=str).encode()
                    ctype = "application/json"
                elif route == "/trace" and tracer is not None:
                    from ..obs import chrome as _chrome
                    body = _json.dumps(
                        _chrome.to_chrome(tracer.records())).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):        # quiet server
                pass

        self._srv = http.server.ThreadingHTTPServer((host, port), H)
        self.address = self._srv.server_address[:2]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._owns_history and self._history is not None:
            self._history.close()
