"""Tail-tolerant JSONL reading, shared by every append-only log.

Three subsystems append flushed JSON lines and expect a SIGKILLed
writer to leave at most one torn final record: the checkpoint epoch
ledger (runtime/checkpoint.py), the metrics history ring
(obs/history.py), and flight-recorder traces (obs/chrome.py). The
verify counterexample traces (verify/bridge.py, soak/chaos.py) use the
same format. They all share one resolution rule, implemented here once:

- blank lines are skipped;
- a decode failure on the LAST non-empty line is the expected SIGKILL
  artifact and is dropped silently;
- a decode failure on any earlier line is real corruption and raises —
  ``json.JSONDecodeError`` by default, or ``ValueError`` naming
  ``<label>:<lineno>`` when the caller passes ``label`` (the trace
  readers' convention).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence


def parse_jsonl_lines(lines: Sequence[str],
                      label: Optional[str] = None) -> List[dict]:
    """Decode JSONL lines under the shared torn-tail rule above."""
    nonempty = [(i, ln) for i, ln in enumerate(lines) if ln.strip()]
    out: List[dict] = []
    for pos, (i, ln) in enumerate(nonempty):
        try:
            out.append(json.loads(ln))
        except json.JSONDecodeError:
            if pos == len(nonempty) - 1:
                break        # SIGKILL artifact: torn final append
            if label is not None:
                raise ValueError(
                    f"{label}:{i + 1}: undecodable record "
                    f"(not a truncated tail)")
            raise
    return out


def read_jsonl(path: str, label: Optional[str] = None) -> List[dict]:
    """Read a JSONL file tail-tolerantly; a missing file is an empty
    log (the first append creates it)."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        lines = f.read().splitlines()
    return parse_jsonl_lines(lines, label=label)
