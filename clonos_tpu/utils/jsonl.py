"""Durable JSONL: one reader rule, one writer discipline.

Every append-only log in the repo — the checkpoint epoch ledger
(runtime/checkpoint.py), the metrics history ring (obs/history.py),
the causal timeline (obs/timeline.py), the autoscale decision-log
sidecar (autoscale/controller.py), flight-recorder traces
(obs/chrome.py), incident bundles (obs/incident.py) — shares the same
crash model: a SIGKILLed writer leaves at most one torn final record.
This module implements both halves of that contract once.

**Reading** (:func:`parse_jsonl_lines`, :func:`read_jsonl`,
:func:`iter_jsonl`):

- blank lines are skipped;
- a decode failure on the LAST non-empty line is the expected SIGKILL
  artifact and is dropped silently;
- a decode failure on any earlier line is real corruption and raises —
  ``json.JSONDecodeError`` by default, or ``ValueError`` naming
  ``<label>:<lineno>`` when the caller passes ``label`` (the trace
  readers' convention).

**Writing** (:class:`JsonlAppender`, :func:`atomic_rewrite_jsonl`):

- one lazily-opened append handle per file, every record flushed to
  the OS as it lands (a clean exit loses nothing, a SIGKILL at most
  the line being written);
- fsync policy is explicit per log: ``fsync_every=0`` (flush only —
  observability logs) or group-commit every K appends with
  :meth:`JsonlAppender.sync` at durability points (the ledger's
  discipline);
- whole-file rewrites (compaction, last-wins) go through
  :func:`atomic_rewrite_jsonl`: tmp + fsync + ``os.replace``, so a
  crash mid-rewrite leaves the old file or the new one, never a mix.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Iterable, Iterator, List, Optional, Sequence


def parse_jsonl_lines(lines: Sequence[str],
                      label: Optional[str] = None) -> List[dict]:
    """Decode JSONL lines under the shared torn-tail rule above."""
    nonempty = [(i, ln) for i, ln in enumerate(lines) if ln.strip()]
    out: List[dict] = []
    for pos, (i, ln) in enumerate(nonempty):
        try:
            out.append(json.loads(ln))
        except json.JSONDecodeError:
            if pos == len(nonempty) - 1:
                break        # SIGKILL artifact: torn final append
            if label is not None:
                raise ValueError(
                    f"{label}:{i + 1}: undecodable record "
                    f"(not a truncated tail)")
            raise
    return out


def read_jsonl(path: str, label: Optional[str] = None) -> List[dict]:
    """Read a JSONL file tail-tolerantly; a missing file is an empty
    log (the first append creates it)."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        lines = f.read().splitlines()
    return parse_jsonl_lines(lines, label=label)


def iter_jsonl(path: str, label: Optional[str] = None) -> Iterator[dict]:
    """Stream a JSONL file record by record under the same torn-tail
    rule, holding O(1) lines in memory — the cursor behind the k-way
    timeline merge, where materializing every process's file defeats
    the bound."""
    if not os.path.exists(path):
        return
    with open(path) as f:
        lineno = 0
        for ln in f:
            lineno += 1
            if not ln.strip():
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                # Torn tail only if nothing non-empty follows.
                if not any(rest.strip() for rest in f):
                    return
                if label is not None:
                    raise ValueError(
                        f"{label}:{lineno}: undecodable record "
                        f"(not a truncated tail)")
                raise
            yield rec


class JsonlAppender:
    """The one durable JSONL append handle.

    Lazily opens ``path`` for append on the first record; every
    :meth:`append` writes one ``json.dumps`` line and flushes it to
    the OS. ``fsync_every=K`` batches the fsync every K appends (the
    ledger's group-commit); ``fsync_every=0`` never fsyncs on its own
    — either way :meth:`sync` forces the tail durable at an explicit
    durability point. Thread-safe; serialization knobs (``sort_keys``,
    ``default``) are per-log policy fixed at construction so every
    append of a log encodes the same way.
    """

    def __init__(self, path: str, *, sort_keys: bool = False,
                 default=None, fsync_every: int = 0):
        self.path = path
        self._sort_keys = bool(sort_keys)
        self._default = default
        self.fsync_every = int(fsync_every)
        self._file = None
        self._unsynced = 0
        self._lock = threading.Lock()
        #: lines appended through this handle (compaction triggers)
        self.appended = 0

    def append(self, rec) -> None:
        line = json.dumps(rec, sort_keys=self._sort_keys,
                          default=self._default) + "\n"
        with self._lock:
            if self._file is None:
                self._file = open(self.path, "a")
            self._file.write(line)
            self._file.flush()
            self.appended += 1
            self._unsynced += 1
            if self.fsync_every and self._unsynced >= self.fsync_every:
                os.fsync(self._file.fileno())
                self._unsynced = 0

    @property
    def unsynced(self) -> int:
        """Appends flushed but not yet fsynced — the group-commit
        batch window a SIGKILL could still tear."""
        return self._unsynced

    def sync(self) -> None:
        """fsync any unsynced tail (a durability point: checkpoint
        completion, bundle landing)."""
        with self._lock:
            if self._file is not None and self._unsynced:
                os.fsync(self._file.fileno())
                self._unsynced = 0

    def close(self, sync: bool = True) -> None:
        """Close the handle (fsyncing the tail unless told not to);
        appending again reopens it — compaction swaps the inode under
        us via :func:`atomic_rewrite_jsonl`, so the handle must drop."""
        with self._lock:
            if self._file is not None:
                if sync and self._unsynced:
                    os.fsync(self._file.fileno())
                self._unsynced = 0
                self._file.close()
                self._file = None


def atomic_rewrite_jsonl(path: str, records: Iterable[dict], *,
                         sort_keys: bool = False, default=None) -> int:
    """Replace ``path`` with exactly ``records``, atomically: write a
    sibling tmp, flush + fsync it, then ``os.replace`` — a crash at any
    point leaves the old file or the new one. Returns the record
    count. Callers holding a :class:`JsonlAppender` on ``path`` must
    :meth:`~JsonlAppender.close` it first (the inode swaps)."""
    tmp = path + ".tmp"
    n = 0
    with open(tmp, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=sort_keys,
                               default=default) + "\n")
            n += 1
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return n
