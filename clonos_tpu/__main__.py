from clonos_tpu.cli import main

raise SystemExit(main())
