"""In-flight log: retained output batches for replay after downstream failure.

Capability parity with the reference's ``inflightlogging`` package
(flink-runtime .../inflightlogging — InFlightLog.java API: log/getIterator/
notifyCheckpointComplete; InMemorySubpartitionInFlightLogger.java;
SpillableSubpartitionInFlightLogger.java:45 with per-epoch spill files so a
completed checkpoint deletes its file; SpilledReplayIterator.java:61 with
prefetch threads) — re-designed for TPU:

- The hot path is a **device ring**: one edge's routed output batches are a
  ``[S, P, cap]`` tensor ring over supersteps, appended in the jitted step
  (same absolute-offset/epoch-index scheme as the causal log — see
  causal/log.py). Replay of the last epochs is a device-side slice feed —
  no host round trip for the common in-HBM case.
- **Spill** runs at epoch boundaries on the host: the just-finished epoch's
  step range is device_get as one contiguous block and written to one file
  per epoch (truncation == file delete, exactly the reference's trick).
  HBM->host DRAM->disk instead of JVM heap->disk.
- **Replay** for spilled epochs is a producer/consumer iterator with a
  prefetch thread (SpilledReplayIterator analog) that streams epoch files
  back as device arrays in step order.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from clonos_tpu.api.records import RecordBatch


class EdgeLogState(NamedTuple):
    """Device ring of one edge's routed batches, indexed by absolute
    superstep count. Offsets follow causal/log.py discipline: absolute,
    monotonic; ring position = offset & (S-1); truncation moves ``tail``."""

    keys: jnp.ndarray         # int32[S, P, cap]
    values: jnp.ndarray       # int32[S, P, cap]
    timestamps: jnp.ndarray   # int32[S, P, cap]
    valid: jnp.ndarray        # bool[S, P, cap]
    head: jnp.ndarray         # int32 scalar: absolute steps appended
    tail: jnp.ndarray         # int32 scalar: oldest retained step
    epoch_starts: jnp.ndarray # int32[max_epochs]
    epoch_base: jnp.ndarray   # int32 scalar
    latest_epoch: jnp.ndarray # int32 scalar

    @property
    def ring_steps(self) -> int:
        return self.keys.shape[0]

    @property
    def max_epochs(self) -> int:
        return self.epoch_starts.shape[0]


def create(ring_steps: int, parallelism: int, capacity: int,
           max_epochs: int) -> EdgeLogState:
    if ring_steps & (ring_steps - 1):
        raise ValueError(f"ring_steps must be a power of two, got {ring_steps}")
    z = jnp.asarray(0, jnp.int32)
    shape = (ring_steps, parallelism, capacity)
    return EdgeLogState(
        keys=jnp.zeros(shape, jnp.int32), values=jnp.zeros(shape, jnp.int32),
        timestamps=jnp.zeros(shape, jnp.int32),
        valid=jnp.zeros(shape, jnp.bool_),
        head=z, tail=z, epoch_starts=jnp.zeros((max_epochs,), jnp.int32),
        epoch_base=z, latest_epoch=z)


def size(state: EdgeLogState) -> jnp.ndarray:
    return state.head - state.tail


def overflowed(state: EdgeLogState) -> jnp.ndarray:
    """True when un-truncated (and un-spilled) steps exceed the ring — the
    control plane must spill or checkpoint before this bites (the JVM analog
    is the buffer pool running dry, which *blocks* the producer; here the
    executor's epoch loop checks and stalls)."""
    return size(state) > state.ring_steps


def append_step(state: EdgeLogState, batch: RecordBatch) -> EdgeLogState:
    """Log one superstep's routed batch (reference InFlightLog.log)."""
    pos = state.head & (state.ring_steps - 1)
    return state._replace(
        keys=state.keys.at[pos].set(batch.keys),
        values=state.values.at[pos].set(batch.values),
        timestamps=state.timestamps.at[pos].set(batch.timestamps),
        valid=state.valid.at[pos].set(batch.valid),
        head=state.head + 1)


def append_block(state: EdgeLogState, block: RecordBatch) -> EdgeLogState:
    """Log a whole block of K steps' batches ([K, P, cap] leaves) in one
    scatter — the block-executor bulk path. K must be <= ring_steps (the
    executor enforces this), so ring positions are unique."""
    K = block.keys.shape[0]
    idx = (state.head + jnp.arange(K, dtype=jnp.int32)) & (state.ring_steps - 1)
    return state._replace(
        keys=state.keys.at[idx].set(block.keys, unique_indices=True),
        values=state.values.at[idx].set(block.values, unique_indices=True),
        timestamps=state.timestamps.at[idx].set(block.timestamps,
                                                unique_indices=True),
        valid=state.valid.at[idx].set(block.valid, unique_indices=True),
        head=state.head + K)


def start_epoch(state: EdgeLogState, epoch_id) -> EdgeLogState:
    """Record epoch ``epoch_id``'s replay-start offset (= ``head``, the
    fence). The batch appended at the fence's last step is still *in
    flight* (depth-1 pipeline — its consumer reads it one step after the
    fence), but that fence-spanning batch is checkpointed as the edge
    buffer of the LeanSnapshot, so the ring needs to retain only the
    post-fence steps (the aligned-barrier boundary condition the reference
    gets from barriers flowing through the pipeline rides the snapshot
    instead of the log)."""
    e = jnp.asarray(epoch_id, jnp.int32)
    slot = e % state.max_epochs
    return state._replace(
        epoch_starts=state.epoch_starts.at[slot].set(state.head),
        latest_epoch=jnp.maximum(state.latest_epoch, e))


def epoch_start_step(state: EdgeLogState, epoch_id) -> jnp.ndarray:
    e = jnp.asarray(epoch_id, jnp.int32)
    return state.epoch_starts[e % state.max_epochs]


def truncate(state: EdgeLogState, completed_epoch) -> EdgeLogState:
    """Checkpoint complete: drop steps of epochs <= completed_epoch
    (reference notifyCheckpointComplete -> per-epoch file delete)."""
    e = jnp.asarray(completed_epoch, jnp.int32)
    new_tail = jnp.maximum(epoch_start_step(state, e + 1), state.tail)
    return state._replace(tail=new_tail,
                          epoch_base=jnp.maximum(e + 1, state.epoch_base))


def slice_steps_at(state: EdgeLogState, abs_step, max_out: int
                   ) -> RecordBatch:
    """Gather ``max_out`` steps from exactly ``abs_step`` with NO tail
    clamp: slots before the ring tail come back as whatever the ring
    holds there (stale or clobbered) — the caller must mask them. Used
    by recovery's uniform replay windows, whose first window starts one
    slot before the fence (that dead slot is replaced by the
    checkpointed edge buffer; see cluster._replay_inputs)."""
    start = jnp.asarray(abs_step, jnp.int32)
    count = jnp.clip(state.head - start, 0, max_out)
    idx = jnp.arange(max_out, dtype=jnp.int32)
    pos = (start + idx) & (state.ring_steps - 1)
    live = (idx < count)[:, None, None]
    return RecordBatch(
        keys=jnp.where(live, state.keys[pos], 0),
        values=jnp.where(live, state.values[pos], 0),
        timestamps=jnp.where(live, state.timestamps[pos], 0),
        valid=jnp.where(live, state.valid[pos], False))


def slice_steps(state: EdgeLogState, abs_step, max_out: int
                ) -> Tuple[RecordBatch, jnp.ndarray, jnp.ndarray]:
    """Gather up to ``max_out`` retained steps from ``abs_step``. Returns
    (stacked RecordBatch [max_out, P, cap], count, start). The replay feed
    (reference getInFlightIterator)."""
    start = jnp.maximum(jnp.asarray(abs_step, jnp.int32), state.tail)
    count = jnp.clip(state.head - start, 0, max_out)
    idx = jnp.arange(max_out, dtype=jnp.int32)
    pos = (start + idx) & (state.ring_steps - 1)
    live = (idx < count)[:, None, None]
    batch = RecordBatch(
        keys=jnp.where(live, state.keys[pos], 0),
        values=jnp.where(live, state.values[pos], 0),
        timestamps=jnp.where(live, state.timestamps[pos], 0),
        valid=jnp.where(live, state.valid[pos], False))
    return batch, count, start


# --- host spill path ---------------------------------------------------------


class SpillPolicy:
    """When to move completed-epoch step ranges out of the device ring
    (reference InFlightLogConfig spill.policy eager|availability|epoch)."""

    EAGER = "eager"            # spill every epoch as soon as it closes
    AVAILABILITY = "availability"  # spill when ring occupancy crosses a ratio
    DISABLED = "disabled"      # in-memory only (InMemory logger equivalent)


class SpillingInFlightLog:
    """Host-side owner of one edge's spilled epochs.

    A thin RecordBatch adapter over :class:`storage.TieredEpochStore`
    (the generalized tier fabric shared with the determinant logs): one
    checksummed segment file per epoch so truncation deletes files —
    the reference's SpillableSubpartitionInFlightLogger file-per-epoch
    design. Writes (including the device→host copy) happen on the
    store's background writer; a flush failure keeps the data
    host-resident (reference keeps the buffer in memory on flush
    failure) so replay still works.
    """

    def __init__(self, spool_dir: Optional[str], edge_id: int,
                 policy: str = SpillPolicy.EAGER,
                 availability_trigger: float = 0.3,
                 host_budget_epochs: Optional[int] = 2):
        from clonos_tpu.storage import TieredEpochStore
        self.edge_id = edge_id
        self.policy = policy
        self.availability_trigger = availability_trigger
        self.spool_dir = spool_dir
        self.store = TieredEpochStore(
            spool_dir, f"edge{edge_id}",
            durable=bool(spool_dir) and policy != SpillPolicy.DISABLED,
            host_budget_epochs=host_budget_epochs)

    def _path(self, epoch: int) -> str:
        return self.store.segment_path(epoch)

    def spill_epoch(self, epoch: int, start_step: int,
                    batches: RecordBatch) -> None:
        """Accept one closed epoch's stacked steps ([n, P, cap] per
        field) — device arrays welcome; the d2h copy overlaps the next
        epoch's compute on the store's writer thread."""
        self.store.put(epoch, start_step, {
            "keys": batches.keys, "values": batches.values,
            "timestamps": batches.timestamps, "valid": batches.valid,
        })

    def truncate(self, completed_epoch: int) -> None:
        self.store.truncate(completed_epoch)

    def retained_epochs(self) -> List[int]:
        return self.store.retained_epochs()

    def load_epoch(self, epoch: int) -> Tuple[int, RecordBatch]:
        """Synchronous read of one epoch (start_step, steps[n, P, cap])
        from whichever tier holds it (host buffer or verified disk
        segment)."""
        start, payload = self.store.load_epoch(epoch)
        return start, RecordBatch(
            jnp.asarray(payload["keys"]), jnp.asarray(payload["values"]),
            jnp.asarray(payload["timestamps"]), jnp.asarray(payload["valid"]))

    def attach_digest(self, epoch: int, digest: str) -> None:
        """Pin the audit ledger's ring-channel digest on the spilled
        epoch's segment (diff_ledgers then verifies refills for free)."""
        self.store.attach_digest(epoch, digest)

    def drain(self) -> None:
        """Block until pending spill writes are durable (tests/shutdown)."""
        self.store.drain()

    def close(self) -> None:
        self.store.close()


class ReplayIterator:
    """Prefetching replay of epochs [from_epoch, to_epoch], step-ordered
    (reference SpilledReplayIterator.java:61: producer thread fills
    per-epoch deques; consumer blocks on the deque head).

    ``skip_steps`` skips already-delivered steps of the first epoch
    (reference InFlightLogRequestEvent.numBuffersToSkip dedup)."""

    def __init__(self, log: SpillingInFlightLog, from_epoch: int,
                 to_epoch: int, skip_steps: int = 0, prefetch: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
        self._epochs = [e for e in log.retained_epochs()
                        if from_epoch <= e <= to_epoch]
        self._log = log
        self._skip = skip_steps
        self._stop = False
        self._t = threading.Thread(target=self._produce, daemon=True)
        self._t.start()

    def close(self) -> None:
        """Release the producer if the consumer stops early (a bounded
        prefetch queue would otherwise block the thread forever with an
        epoch batch pinned in memory)."""
        self._stop = True
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def _produce(self):
        # Epoch-granular prefetch: the producer thread reads files ahead
        # while the consumer drains — the reference's async-read deques.
        for e in self._epochs:
            if self._stop:
                return
            try:
                item = self._log.load_epoch(e)
            except Exception as exc:
                # A torn segment (or any refill failure) must reach the
                # CONSUMER: dying here would leave it blocked on the
                # queue forever. The exception rides the queue and
                # re-raises on the consumer thread.
                item = exc
            while not self._stop:
                try:
                    self._q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue
            if self._stop or isinstance(item, Exception):
                return
        while not self._stop:
            try:
                self._q.put(None, timeout=0.1)
                return
            except queue.Full:
                continue

    def epochs(self) -> Iterator[Tuple[int, RecordBatch]]:
        """Prefetched (start_step, stacked steps) per retained epoch —
        the chunk-assembly feed for recovery's spill reads."""
        first = True
        while True:
            item = self._q.get()
            if item is None:
                return
            if isinstance(item, Exception):
                raise item
            start, batch = item
            if first and self._skip:
                start = start + self._skip
                batch = jax.tree_util.tree_map(
                    lambda x: x[self._skip:], batch)
            first = False
            yield start, batch

    def __iter__(self) -> Iterator[Tuple[int, RecordBatch]]:
        for start, batch in self.epochs():
            n = batch.keys.shape[0]
            for i in range(n):
                yield (start + i, jax.tree_util.tree_map(
                    lambda x, i=i: x[i], batch))
