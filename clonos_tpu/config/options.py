"""Typed configuration system.

Equivalent capability to the reference's ``ConfigOption``/``Configuration``
(flink-core .../configuration/ConfigOptions.java) and per-job
``ExecutionConfig`` (flink-core .../api/common/ExecutionConfig.java), but a
small idiomatic-Python design: frozen option descriptors with typed defaults,
a ``Configuration`` mapping that validates on read, and dataclass-style
snapshots for shipping into jitted code (only static hashables cross the jit
boundary).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Generic, Iterator, Mapping, Optional, TypeVar

T = TypeVar("T")


@dataclasses.dataclass(frozen=True)
class ConfigOption(Generic[T]):
    """A typed, documented configuration key with a default."""

    key: str
    default: T
    type: type = object
    description: str = ""
    validator: Optional[Callable[[T], bool]] = None

    def __post_init__(self):
        if self.type is object and self.default is not None:
            object.__setattr__(self, "type", builtin_type(self.default))
        self.check(self.default)

    def check(self, value: T) -> T:
        if value is not None and self.type is not object:
            if self.type is float and isinstance(value, int) and not isinstance(value, bool):
                value = float(value)  # type: ignore[assignment]
            if self.type is int and isinstance(value, bool):
                raise TypeError(
                    f"config key {self.key!r} expects int, got bool: {value!r}")
            if not isinstance(value, self.type):
                raise TypeError(
                    f"config key {self.key!r} expects {self.type.__name__}, "
                    f"got {type(value).__name__}: {value!r}"
                )
        if self.validator is not None and value is not None and not self.validator(value):
            raise ValueError(f"invalid value for config key {self.key!r}: {value!r}")
        return value


def builtin_type(v: Any) -> type:
    # bool is a subclass of int; keep it distinct so int options reject bools.
    return bool if isinstance(v, bool) else type(v)


class Configuration:
    """String-keyed config map with typed reads via :class:`ConfigOption`."""

    def __init__(self, values: Optional[Mapping[str, Any]] = None):
        self._values: Dict[str, Any] = dict(values or {})

    def get(self, option: ConfigOption[T]) -> T:
        if option.key in self._values:
            return option.check(self._values[option.key])
        return option.default

    def set(self, option: ConfigOption[T], value: T) -> "Configuration":
        self._values[option.key] = option.check(value)
        return self

    def set_raw(self, key: str, value: Any) -> "Configuration":
        self._values[key] = value
        return self

    def contains(self, option: ConfigOption) -> bool:
        return option.key in self._values

    def merged_with(self, other: "Configuration") -> "Configuration":
        out = Configuration(self._values)
        out._values.update(other._values)
        return out

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._values)

    def __iter__(self) -> Iterator[str]:
        return iter(self._values)

    def __repr__(self) -> str:
        return f"Configuration({self._values!r})"
