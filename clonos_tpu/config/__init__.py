from clonos_tpu.config.options import ConfigOption, Configuration
from clonos_tpu.config import defaults

__all__ = ["ConfigOption", "Configuration", "defaults"]
