"""The framework's configuration surface.

Covers the reference's Clonos-specific keys (SURVEY §2.3 config row:
flink-runtime .../configuration/JobManagerOptions.java:111-135, NettyConfig
.java:82-98, ExecutionConfig.java:297-310, InFlightLogConfig.java:42-71) plus
the TPU-native knobs this framework adds (log capacities, batch shapes, mesh
axes).
"""

from __future__ import annotations

from clonos_tpu.config.options import ConfigOption

# --- failover / standby (reference: JobManagerOptions.java:111-135) ---------

FAILOVER_STRATEGY = ConfigOption(
    "jobmanager.execution.failover-strategy", "standbytask",
    description="Failover strategy: 'standbytask' (Clonos local recovery) or "
                "'full' (global restart).")

NUM_STANDBY_TASKS = ConfigOption(
    "jobmanager.execution.num-standby-tasks", 1,
    description="Passive standby replicas per subtask, state-synced via "
                "checkpoint pushes.")

CHECKPOINT_BACKOFF_BASE_MS = ConfigOption(
    "jobmanager.execution.checkpoint-backoff-base", 1000,
    description="Base backoff (ms) applied to the checkpoint interval while "
                "a recovery is in progress.")

CHECKPOINT_BACKOFF_MULTIPLIER = ConfigOption(
    "jobmanager.execution.checkpoint-backoff-multiplier", 2.0,
    description="Multiplier on the checkpoint interval during recovery.")

# --- determinant sharing (reference: ExecutionConfig.java:297-310) ----------

DETERMINANT_SHARING_DEPTH = ConfigOption(
    "causal.determinant-sharing-depth", -1,
    description="How many hops downstream determinants are replicated. "
                "-1 = full sharing (survive any number of connected "
                "failures); k = survive up to k connected failures.")

DELTA_ENCODING_STRATEGY = ConfigOption(
    "causal.delta-encoding-strategy", "grouped",
    validator=lambda v: v in ("flat", "grouped"),
    description="Piggyback delta layout: 'flat' (one entry per thread log) "
                "or 'grouped' (vertex->partition->subpartition hierarchy).")

# --- determinant log memory (reference: NettyConfig.java:82-98) -------------

DETERMINANT_LOG_CAPACITY = ConfigOption(
    "causal.log.capacity", 1 << 16,
    description="Slots per thread causal log ring buffer (device HBM). "
                "Must be a power of two.",
    validator=lambda v: v > 0 and (v & (v - 1)) == 0)

DETERMINANT_MAX_EPOCHS = ConfigOption(
    "causal.log.max-epochs", 64,
    description="Maximum concurrently-retained (un-truncated) epochs per log.",
    validator=lambda v: v > 0)

DETERMINANT_MAX_DELTA = ConfigOption(
    "causal.log.max-delta", 4096,
    description="Static upper bound on determinants shipped per piggyback "
                "delta (one superstep's worth).")

# --- in-flight log (reference: InFlightLogConfig.java:42-71) ----------------

INFLIGHT_TYPE = ConfigOption(
    "taskmanager.inflight.type", "inmemory",
    validator=lambda v: v in ("spillable", "inmemory", "disabled"),
    description="In-flight log implementation.")

INFLIGHT_SPILL_POLICY = ConfigOption(
    "taskmanager.inflight.spill.policy", "eager",
    validator=lambda v: v in ("eager", "availability", "epoch"),
    description="When to spill epochs from HBM to host memory/disk.")

INFLIGHT_PREFETCH_BUFFERS = ConfigOption(
    "taskmanager.inflight.spill.num-prefetch-buffers", 50,
    description="Replay prefetch depth for spilled epochs.")

INFLIGHT_AVAILABILITY_TRIGGER = ConfigOption(
    "taskmanager.inflight.spill.availability-trigger", 0.3,
    description="Pool availability fraction below which 'availability' "
                "policy spills.")

INFLIGHT_HOST_BUDGET_EPOCHS = ConfigOption(
    "taskmanager.inflight.spill.host-budget-epochs", 2,
    description="Sealed epochs each spill owner keeps resident in the host "
                "staging tier once their segments are durable; older "
                "epochs demote to disk-only (storage/tiered.py).")

INFLIGHT_CAPACITY_BATCHES = ConfigOption(
    "taskmanager.inflight.capacity-batches", 256,
    description="Batches retained per edge in the device-resident in-flight "
                "ring.")

# --- checkpointing ----------------------------------------------------------

CHECKPOINT_INTERVAL_STEPS = ConfigOption(
    "checkpoint.interval-steps", 16,
    description="Supersteps per epoch (checkpoint barrier cadence).")

CHECKPOINT_DIR = ConfigOption(
    "checkpoint.dir", "/tmp/clonos_tpu/checkpoints",
    description="Durable storage root for snapshots and spilled epochs.")

# --- execution / batching (TPU-native) --------------------------------------

BATCH_SIZE = ConfigOption(
    "execution.batch-size", 256,
    description="Records per batch flowing along each edge per superstep. "
                "The TPU analog of the reference's network buffer.")

RECORD_WIDTH = ConfigOption(
    "execution.record-width", 8,
    description="int32 lanes per record in the packed record layout.")

MESH_TASK_AXIS = ConfigOption(
    "parallel.mesh-task-axis", "tasks",
    description="Mesh axis name over which parallel subtasks are sharded.")

HEARTBEAT_INTERVAL_MS = ConfigOption(
    "heartbeat.interval", 1000,
    description="Heartbeat cadence between control plane and task plane.")

HEARTBEAT_TIMEOUT_MS = ConfigOption(
    "heartbeat.timeout", 5000,
    description="Missed-heartbeat window before a task executor is declared "
                "failed.")

# --- observability (clonos_tpu/obs) -----------------------------------------

TRACING_ENABLED = ConfigOption(
    "observability.tracing.enabled", False,
    description="Record distributed trace spans (epoch/checkpoint/recovery "
                "lifecycles) and propagate trace context on control-wire "
                "headers. Off = the NullTracer: no wire fields, no "
                "per-record work.")

TRACE_DIR = ConfigOption(
    "observability.tracing.dir", "/tmp/clonos_tpu/traces",
    description="Directory for per-process trace-<service>.jsonl files "
                "(convert with `clonos_tpu trace`).")

TRACE_BUFFER_EVENTS = ConfigOption(
    "observability.tracing.buffer-events", 8192,
    validator=lambda v: v > 0,
    description="Flight-recorder ring size: most recent trace records kept "
                "in memory and served on the metrics endpoint's /trace.")

AUDIT_ENABLED = ConfigOption(
    "observability.audit.enabled", False,
    description="Seal a per-epoch audit digest at every checkpoint barrier, "
                "persist the epoch ledger next to the checkpoints, and "
                "validate replayed epochs against it during recovery. Off = "
                "the NullAuditor: no digest reads, no ledger writes, no "
                "wire fields.")

AUDIT_ON_DIVERGENCE = ConfigOption(
    "observability.audit.on-divergence", "warn",
    validator=lambda v: v in ("warn", "abort"),
    description="What a replay-divergence audit finding does: 'warn' emits "
                "the recovery.audit.divergence instant and counts it; "
                "'abort' additionally fails the recovery "
                "(AuditDivergenceError) before the job resumes on "
                "non-reproduced state.")

PROFILE_ENABLED = ConfigOption(
    "observability.profile.enabled", False,
    description="Attribute per-section fault-tolerance overhead "
                "(overhead.<section>-ms histograms + the "
                "overhead.ft-fraction gauge) with device-fenced section "
                "timers in the hot paths. Off = the NullProfiler: no "
                "fencing, no per-step host work.")

METRICS_HISTORY_INTERVAL_S = ConfigOption(
    "observability.metrics-history.interval-s", 2.0,
    validator=lambda v: v > 0,
    description="Seconds between metrics-history samples taken by the "
                "metrics endpoint's sampler thread (served at "
                "/metrics/history.json).")

METRICS_HISTORY_WINDOW = ConfigOption(
    "observability.metrics-history.window", 512,
    validator=lambda v: v > 0,
    description="Samples retained in the metrics-history ring (memory and "
                "the bounded history JSONL file alike).")
