"""Test: (a) lane padding of [..., 8] arrays, (b) int mod cost, (c) layouts."""
import time
import jax, jax.numpy as jnp
import numpy as np

def bench(label, fn, *args, n=5):
    r = jax.block_until_ready(fn(*args))
    t0 = time.monotonic()
    for _ in range(n):
        r = fn(*args)
    jax.block_until_ready(r)
    print(f"{label}: {(time.monotonic()-t0)/n*1e3:.2f} ms")

dev = jax.devices()[0]

def mem():
    s = dev.memory_stats()
    return s.get("bytes_in_use", 0) if s else 0

m0 = mem()
a = jax.block_until_ready(jnp.zeros((384, 32768, 8), jnp.int32))
m1 = mem()
print(f"[384,32768,8] int32: logical {384*32768*8*4/1e6:.0f} MB, "
      f"actual {(m1-m0)/1e6:.0f} MB")
del a
b = jax.block_until_ready(jnp.zeros((384, 8, 32768), jnp.int32))
m2 = mem()
print(f"[384,8,32768] int32: actual {(m2-m1)/1e6:.0f} MB")
del b
c = jax.block_until_ready(jnp.zeros((384, 32768 * 8), jnp.int32))
m3 = mem()
print(f"[384,262144] int32: actual {(m3-m2)/1e6:.0f} MB")
del c

# copy cost by layout
for shape in [(384, 32768, 8), (384, 8, 32768), (384, 262144)]:
    x = jnp.zeros(shape, jnp.int32)
    f = jax.jit(lambda x: x + 1)
    bench(f"add1 {shape}", f, x)

# scatter along dim1 with trailing 8 vs trailing-major layout
idx = jnp.arange(2048, dtype=jnp.int32) + 5
blk_a = jnp.ones((384, 2048, 8), jnp.int32)
rep_a = jnp.zeros((384, 32768, 8), jnp.int32)
f_a = jax.jit(lambda r, b: r.at[:, idx].set(b, unique_indices=True))
bench("scatter [384,2048,8] into [384,32768,8]", f_a, rep_a, blk_a)

blk_b = jnp.ones((384, 8, 2048), jnp.int32)
rep_b = jnp.zeros((384, 8, 32768), jnp.int32)
f_b = jax.jit(lambda r, b: r.at[:, :, idx].set(b, unique_indices=True))
bench("scatter [384,8,2048] into [384,8,32768]", f_b, rep_b, blk_b)

f_c = jax.jit(lambda r, b: jax.lax.dynamic_update_slice(r, b, (0, 5, 0)))
bench("DUS [384,2048,8] into [384,32768,8]", f_c, rep_a, blk_a)
f_d = jax.jit(lambda r, b: jax.lax.dynamic_update_slice(
    r, b, (0, 0, jnp.asarray(5, jnp.int32))))
bench("DUS [384,8,2048] into [384,8,32768]", f_d, rep_b, blk_b)

# int hash parts on [512,8,128]
seq = jnp.arange(512 * 8 * 128, dtype=jnp.int32).reshape(512, 8, 128)
bench("u32 mul-hash only", jax.jit(
    lambda s: ((s.astype(jnp.uint32) ^ (s.astype(jnp.uint32) >> 16))
               * jnp.uint32(0x7FEB352D)).astype(jnp.int32)), seq)
bench("mod 997", jax.jit(
    lambda s: (s.astype(jnp.uint32) % jnp.uint32(997)).astype(jnp.int32)), seq)
bench("mod 997 via f64-free trick", jax.jit(
    lambda s: (s - (s // 997) * 997)), seq)
# mul-shift modulo alternative (keys uniform enough): take low bits * K >> 32
bench("mulhi range-map", jax.jit(
    lambda s: ((s.astype(jnp.uint32).astype(jnp.uint64) * 997) >> 32)
    .astype(jnp.int32)), seq)
