"""Reliable chained microbenchmarks: y = fn(y) iterated inside one jit."""
import time
import jax, jax.numpy as jnp
import numpy as np
from functools import partial

def bench_chain(label, fn, x0, iters=20, per_steps=1, n=3):
    @jax.jit
    def run(x):
        return jax.lax.fori_loop(0, iters, lambda i, x: fn(x, i), x)
    r = jax.block_until_ready(run(x0))
    t0 = time.monotonic()
    for _ in range(n):
        r = run(r)
    jax.block_until_ready(r)
    dt = (time.monotonic() - t0) / n / iters
    print(f"{label}: {dt*1e3:.3f} ms/iter ({dt/per_steps*1e6:.2f} us/step)")
    return dt

KK, N, T, CAP, K = 512, 8192, 8, 1024, 997

# A. batched argsort over a block of steps
def f_sort(x, i):
    s = jnp.argsort((x + i) % T, axis=1, stable=True).astype(jnp.int32)
    return (x + s) % 1024
bench_chain(f"argsort [{KK},{N}] (block of {KK} steps)", f_sort,
            jnp.ones((KK, N), jnp.int32), per_steps=KK)

# B. batched cumsum route
def f_route(x, i):
    tgt = (x + i) % T
    oh = (tgt[..., None] == jnp.arange(T)[None, None, :]).astype(jnp.int32)
    pos = jnp.cumsum(oh, axis=1)
    p = jnp.take_along_axis(pos, tgt[..., None], axis=2)[..., 0] - 1
    keep = p < CAP
    row = jnp.where(keep, tgt, T)
    col = jnp.where(keep, p, 0)
    step = jnp.broadcast_to(jnp.arange(KK)[:, None], (KK, N))
    out = jnp.zeros((KK, T + 1, CAP), jnp.int32).at[
        step, row, col].set(x, mode="drop", unique_indices=True)
    return x + out[:, :T, :].reshape(KK, N)
bench_chain(f"cumsum-route [{KK},{N}]", f_route,
            jnp.ones((KK, N), jnp.int32), iters=10, per_steps=KK)

# C. big hash over [KK,8,128]
def f_hash(x, i):
    u = (x + i).astype(jnp.uint32)
    u = (u ^ (u >> 16)) * jnp.uint32(0x7FEB352D)
    u = (u ^ (u >> 15)) * jnp.uint32(0x846CA68B)
    return (u % jnp.uint32(997)).astype(jnp.int32)
bench_chain(f"hash+mod [{KK},8,128]", f_hash,
            jnp.ones((KK, 8, 128), jnp.int32), per_steps=KK)

# D. matmuls chained, varying size
for M in (128, 512, 1024):
    def f_mm(x, i, M=M):
        return (x @ x) * 0.999 + 1e-6
    bench_chain(f"matmul {M}x{M} f32", f_mm,
                jnp.eye(M, dtype=jnp.float32) * 0.5, iters=50)

# E. per-step contribs scatter for a block
def f_contrib(x, i):
    keys = (x + i) % K
    z = jnp.zeros((KK, T, K), jnp.int32)
    step = jnp.broadcast_to(jnp.arange(KK)[:, None, None], keys.shape)
    sub = jnp.broadcast_to(jnp.arange(T)[None, :, None], keys.shape)
    out = z.at[step, sub, keys].add(1, mode="drop")
    return x + out[:, :, :128]
bench_chain(f"contrib scatter [{KK},8,128]->[{KK},8,{K}]", f_contrib,
            jnp.ones((KK, T, 128), jnp.int32), iters=10, per_steps=KK)

# F. prefix cumsum over steps
def f_prefix(x, i):
    return jnp.cumsum(x, axis=0) % 1000 + i
bench_chain(f"cumsum-over-steps [{KK},8,{K}]", f_prefix,
            jnp.ones((KK, T, K), jnp.int32), iters=10, per_steps=KK)

# G. bulk log append (big DUS into ring) chained
L = 32
def f_bulk(s, i):
    ring, head = s
    blk = jnp.full((L, 4 * KK, 8), head, jnp.int32)
    idx = (head + jnp.arange(4 * KK)) & 32767
    return (ring.at[:, idx].set(blk, unique_indices=True), head + 4 * KK)
bench_chain("bulk log append [32,2048,8] into [32,32768,8]", f_bulk,
            (jnp.zeros((L, 32768, 8), jnp.int32), jnp.asarray(0, jnp.int32)),
            iters=10, per_steps=KK)

# H. replica bulk append (gather 384 owners + DUS)
own = jnp.asarray(np.random.randint(0, L, 384), jnp.int32)
def f_rep(s, i):
    rep, head = s
    blk = jnp.full((L, 4 * KK, 8), head, jnp.int32)
    r = blk[own]
    idx = (head + jnp.arange(4 * KK)) & 32767
    return (rep.at[:, idx].set(r, unique_indices=True), head + 4 * KK)
bench_chain("replica bulk append [384,2048,8]", f_rep,
            (jnp.zeros((384, 32768, 8), jnp.int32), jnp.asarray(0, jnp.int32)),
            iters=10, per_steps=KK)
