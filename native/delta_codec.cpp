// Native hot path for the determinant-delta wire codec
// (clonos_tpu/causal/serde.py): CRC32 over packed int32 row blocks and
// bulk frame assembly. The reference keeps its wire hot path on Netty
// direct buffers (io/network/netty/NettyMessage.java:156-242); here the
// compute path is JAX/XLA and the *runtime* byte path is C++, loaded via
// ctypes (no pybind11 in the image).
//
// Build: cc -O3 -shared -fPIC -o libdelta_codec.so delta_codec.cpp
// (clonos_tpu/ops/native.py builds it on first import and falls back to
// pure Python when no compiler is available).

#include <cstdint>
#include <cstring>

extern "C" {

// CRC-32 (IEEE 802.3, zlib-compatible) with a runtime-built table.
static uint32_t table[256];
static bool table_ready = false;

static void build_table() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        table[i] = c;
    }
    table_ready = true;
}

uint32_t dc_crc32(const uint8_t* data, uint64_t n) {
    if (!table_ready) build_table();
    uint32_t c = 0xFFFFFFFFu;
    for (uint64_t i = 0; i < n; i++)
        c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// Assemble a FLAT delta frame in one pass: for each entry i, write
// `log_ids[i] (i32) | starts[i] (i32) | n_rows[i] (u32) | rows | crc`.
// `rows_concat` is the row blocks back to back (int32, lanes per row
// fixed). Returns bytes written, or -1 if out_cap too small.
int64_t dc_encode_flat(const int32_t* log_ids, const int32_t* starts,
                       const uint32_t* n_rows, int32_t count,
                       const int32_t* rows_concat, int32_t lanes,
                       uint8_t* out, int64_t out_cap) {
    int64_t pos = 0;
    const int32_t* rp = rows_concat;
    for (int32_t i = 0; i < count; i++) {
        uint64_t nb = (uint64_t)n_rows[i] * lanes * 4;
        if (pos + 12 + (int64_t)nb + 4 > out_cap) return -1;
        std::memcpy(out + pos, &log_ids[i], 4);
        std::memcpy(out + pos + 4, &starts[i], 4);
        std::memcpy(out + pos + 8, &n_rows[i], 4);
        pos += 12;
        std::memcpy(out + pos, rp, nb);
        uint32_t crc = dc_crc32(out + pos, nb);
        pos += (int64_t)nb;
        std::memcpy(out + pos, &crc, 4);
        pos += 4;
        rp += (uint64_t)n_rows[i] * lanes;
    }
    return pos;
}

}  // extern "C"
