#!/usr/bin/env python
"""Headline benchmark: causal-recovery replay rate.

Workload (BASELINE.json north star): a 32-subtask keyed topology
(8 sources -> 8 windows -> 8 reduces -> 8 sinks), ~1M determinants buffered
cluster-wide across two un-truncated epochs; fail a window subtask; run the
full causal-recovery protocol (determinant fetch from downstream replicas,
merge, in-flight input fetch, vectorized on-device replay scan, verified
bit-identical against the recorded log).

Metric: records/sec through the replay path. The reference's replay is a
per-record JVM loop where every replayed record consumes ~1 determinant
(order/timestamp per buffer/record), so JVM determinants/sec ~= JVM
records/sec; ``vs_baseline`` is measured against
JVM_BASELINE_RECORDS_PER_SEC = 1e6 (the reference publishes no numbers —
BASELINE.md — so the baseline is a generous stand-in for a JVM core's
stream-replay rate; north-star target is vs_baseline >= 10).

Prints ONE JSON line.
"""

import json
import os
import sys
import time

import numpy as np

# Persistent XLA compile cache: a restarted job pays ~zero for the
# prewarm compiles (the reference's standby deploy survives restarts).
from clonos_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

JVM_BASELINE_RECORDS_PER_SEC = 1.0e6

# block_until_ready is unreliable on tunneled backends (the r02→r03
# "regression" was timing noise from this) — use the shared d2h sync.
from clonos_tpu.utils.devsync import device_sync  # noqa: E402
from clonos_tpu.soak import slo as _soak_slo  # noqa: E402

PAR = 8                      # per-vertex parallelism -> 32 subtasks
BATCH = 128                  # records per source subtask per superstep
STEPS_PER_EPOCH = int(os.environ.get("BENCH_STEPS_PER_EPOCH", 4096))
#: un-truncated epochs to accumulate the recovery backlog: 4 epochs x
#: 4096 steps x 32 tasks x 4 rows = ~2.1M buffered determinants (>= 2x
#: the BASELINE.json 1M floor; the replay must chew through all of it).
FILL_EPOCHS = int(os.environ.get("BENCH_FILL_EPOCHS", 4))


def build_job():
    from clonos_tpu.api.environment import StreamEnvironment

    env = StreamEnvironment(name="bench-allround", num_key_groups=64,
                            default_edge_capacity=1024)
    (env.synthetic_source(vocab=997, batch_size=BATCH, parallelism=PAR)
        .key_by()
        .window_count(num_keys=997, window_size=1 << 30, name="window")
        .key_by()
        .reduce(num_keys=997, name="reduce")
        .sink())
    return env.build()


def bench_config4():
    """BASELINE config #4: Kafka-like feed source -> keyBy -> window ->
    keyBy -> reduce -> sink, 64 tasks, connected/cascading failures
    (scaled-down steps to bound bench wall-clock; full protocol)."""
    import jax
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.api.feeds import ListFeedReader
    from clonos_tpu.runtime.cluster import ClusterRunner

    P4, B4 = 16, 32
    SPE = int(os.environ.get("BENCH_C4_SPE", 1024))
    env = StreamEnvironment(name="bench-c4", num_key_groups=64,
                            default_edge_capacity=512)
    (env.host_source(batch_size=B4, parallelism=P4)
        .key_by().window_count(num_keys=499, window_size=1 << 30,
                               parallelism=P4)
        .key_by().reduce(num_keys=499, parallelism=P4)
        .sink(parallelism=P4))
    job = env.build()
    rng = np.random.RandomState(5)
    total = 4 * SPE * B4
    feed = ListFeedReader([
        [(int(k), 1) for k in rng.randint(0, 499, total)]
        for _ in range(P4)])
    runner = ClusterRunner(job, steps_per_epoch=SPE,
                           log_capacity=1 << (SPE * 8 - 1).bit_length(),
                           max_epochs=16,
                           inflight_ring_steps=1 << (SPE - 1).bit_length(),
                           seed=5)
    runner.executor.register_feed(0, feed)
    runner.run_epoch(complete_checkpoint=True)
    # Deployed standbys for this topology too: the cascading number
    # should measure the protocol, not XLA compiles or first-execution
    # warmup (prewarm compiles; the drill runs everything hot).
    prewarm_s = runner.prewarm_recovery()
    t_live = time.monotonic()
    runner.run_epoch(complete_checkpoint=False)
    device_sync(runner.executor.carry)
    live_s = time.monotonic() - t_live
    wbase = job.subtask_base(1)
    rbase = job.subtask_base(2)
    # One subtask of EVERY class the measured cascading failure hits —
    # the recovery number must measure the protocol, not warmup.
    runner.failover_drill([1, wbase + 2, rbase + 6])
    device_sync(runner.executor.carry)
    # Cascading connected failures: feed source + window + reduce subtasks
    # on one path (3 vertex classes at once).
    runner.inject_failure([2, wbase + 3, rbase + 7])
    t0 = time.monotonic()
    report = runner.recover()
    device_sync(runner.executor.carry)
    return {
        "subtasks": job.total_subtasks(),
        "failed": list(report.failed_subtasks),
        "steps_replayed": report.steps_replayed,
        "records_replayed": report.records_replayed,
        "recovery_ms": round((time.monotonic() - t0) * 1e3, 1),
        "steady_state_records_per_sec": round(
            SPE * P4 * B4 / live_s, 1),
        "prewarm_s": round(prewarm_s, 1),
    }


def bench_config5():
    """BASELINE config #5: NEXMark-style two-source keyed interval join
    with CausalSerializableService calls, 128 tasks (scaled-down
    determinant volume; external-call sidecar replay exercised)."""
    import jax
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.causal import determinant as det
    from clonos_tpu.runtime.cluster import ClusterRunner

    # BASELINE scale: 10M buffered determinants cluster-wide. 128 tasks x
    # 4 rows/step x (fill_epochs x SPE) steps >= 1e7 -> 20480 backlog
    # steps at the defaults below (5 x 4096).
    P5 = 32
    SPE = int(os.environ.get("BENCH_C5_SPE", 4096))
    fill = int(os.environ.get("BENCH_C5_FILL", 5))
    env = StreamEnvironment(name="bench-c5", num_key_groups=64,
                            default_edge_capacity=256)
    left = env.synthetic_source(vocab=211, batch_size=16,
                                parallelism=P5).key_by()
    right = env.synthetic_source(vocab=211, batch_size=16,
                                 parallelism=P5, name="source-r").key_by()
    (left.join(right, num_keys=211, window=4, interval=1 << 30,
               parallelism=P5)
         .sink(parallelism=P5))
    job = env.build()
    span = fill * SPE
    # At 10M buffered determinants the full bipartite replication (5120
    # holder logs for this topology) would need ~21GB of HBM for replica
    # storage alone — replication_factor=2 is the memory-scalable knob
    # (2 holders per owner per edge: survives any single failure and
    # all non-adjacent doubles; causal/replication.py:53-66).
    runner = ClusterRunner(job, steps_per_epoch=SPE,
                           log_capacity=1 << (span * 4 - 1).bit_length(),
                           max_epochs=16,
                           inflight_ring_steps=1 << (span - 1).bit_length(),
                           replication_factor=2,
                           seed=9)
    # External CausalSerializableService calls on a join subtask: values
    # record to its log (+ sidecar) and replay after failure.
    jbase = job.subtask_base(2)
    sidecar = det.SidecarStore()
    svc = runner.executor.service_factory(jbase + 1, sidecar)
    ext = svc.serializable_service(lambda q: b"answer:" + q)
    runner.run_epoch(complete_checkpoint=True)
    prewarm_s = runner.prewarm_recovery(vertex_ids=[2])   # join class only
    calls_live = [ext.apply(b"q%d" % i) for i in range(3)]
    for _ in range(fill):
        runner.run_epoch(complete_checkpoint=False)
    device_sync(runner.executor.carry)
    runner.failover_drill([jbase])        # join-class rehearsal
    device_sync(runner.executor.carry)
    dets = int(np.sum(runner.executor.log_sizes()))
    runner.inject_failure([jbase + 1])
    t0 = time.monotonic()
    report = runner.recover()
    device_sync(runner.executor.carry)
    # The recovered log must still hold the external-call determinants.
    replayed_async = sum(
        1 for _s, d in report.managers[0].result.async_events
        if d.TAG == det.SERIALIZABLE)
    return {
        "subtasks": job.total_subtasks(),
        "buffered_determinants": dets,
        "external_calls_live": len(calls_live),
        "external_calls_replayed": replayed_async,
        "steps_replayed": report.steps_replayed,
        "records_replayed": report.records_replayed,
        "recovery_ms": round((time.monotonic() - t0) * 1e3, 1),
        "recovery_phase_ms": {k: round(v, 1)
                              for k, v in report.phase_ms.items()},
        "prewarm_s": round(prewarm_s, 1),
    }


def sharing_depth_sweep():
    """THE Clonos trade-off knob (ExecutionConfig.setDeterminantSharingDepth,
    reference .../api/common/ExecutionConfig.java:297-310): replication
    memory vs how many connected failures survive — MEASURED, not
    analytic: each depth runs the bench topology live (its piggyback
    replication overhead lands in steady_state_records_per_sec) and then
    takes a REAL owner+holder connected failure. Depth 1 must fail loudly
    (the only surviving copy of the owner's log died with its holder);
    depth >= 2 must recover. Analytic replica counts stay as columns."""
    from clonos_tpu.causal import recovery as rec_mod
    from clonos_tpu.causal.replication import ReplicationPlan
    from clonos_tpu.runtime.cluster import ClusterRunner

    SPE = 512
    out = []
    for depth in (1, 2, -1):
        from clonos_tpu.api.environment import StreamEnvironment
        env = StreamEnvironment(name=f"bench-depth{depth}",
                                num_key_groups=64,
                                default_edge_capacity=1024)
        (env.synthetic_source(vocab=997, batch_size=BATCH, parallelism=PAR)
            .key_by()
            .window_count(num_keys=997, window_size=1 << 30, name="window")
            .key_by()
            .reduce(num_keys=997, name="reduce")
            .sink())
        job = env.build()
        job.sharing_depth = depth
        # replication_factor=1: ONE holder per owner per depth level, so
        # "survives k connected failures" maps exactly to the depth knob
        # (with full bipartite replication every depth-1 owner has P
        # holders and even owner+holder failures survive — that measures
        # the factor, not the depth).
        plan = ReplicationPlan.from_job(job, depth, replication_factor=1)
        cap = 1 << (SPE * 4 * 2 - 1).bit_length()
        runner = ClusterRunner(job, steps_per_epoch=SPE, log_capacity=cap,
                               max_epochs=16, inflight_ring_steps=1 << 10,
                               block_steps=512, replication_factor=1,
                               seed=7)
        runner.run_epoch(complete_checkpoint=True)
        device_sync(runner.executor.carry)
        t_w = time.monotonic()
        runner.run_epoch(complete_checkpoint=False)
        device_sync(runner.executor.carry)
        live_s = time.monotonic() - t_w
        entry = {
            "depth": depth,
            "replication_factor": 1,
            "replica_logs": plan.num_replicas,
            "replica_bytes": plan.num_replicas * cap * 8 * 4,
            "survives_connected_failures": (
                "any" if depth == -1 else depth),
            "steady_state_records_per_sec": round(
                SPE * PAR * BATCH / live_s, 1),
        }
        # Connected owner+holder failure: the window subtask AND the
        # downstream subtask holding its (depth-1) replica die together.
        wflat = PAR + 1
        holder = next(h for (o, h) in plan.pairs if o == wflat)
        runner.inject_failure([wflat, holder])
        try:
            runner.recover()
            device_sync(runner.executor.carry)
            entry["recovery_ok"] = True
        except rec_mod.RecoveryError as e:
            entry["recovery_ok"] = False
            entry["recovery_error"] = str(e)[:160]
        if depth == 1 and entry["recovery_ok"]:
            entry["recovery_error"] = (
                "UNEXPECTED: depth-1 survived an owner+holder failure")
        out.append(entry)
        del runner
        import gc
        gc.collect()
    return out


def overhead_probe():
    """FT-overhead attribution at bench shapes (obs/profile.py): a
    short PROFILED run. The profiler fences device dispatch to make
    section walls meaningful, which serializes the pipeline — so this
    runs separately, after the headline measurement, and never touches
    the pipelined throughput numbers. Reports the per-epoch
    ``overhead.ft-fraction`` (last closed window, warm) plus the
    lifetime per-section breakdown."""
    import gc
    from clonos_tpu.obs import profile as prof_mod
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP

    SPE = int(os.environ.get("BENCH_PROFILE_SPE", 1024))
    prof_mod.configure_profile()
    try:
        job = build_job()
        need = 2 * SPE * DETS_PER_STEP
        runner = ClusterRunner(
            job, steps_per_epoch=SPE,
            log_capacity=1 << need.bit_length(), max_epochs=16,
            inflight_ring_steps=1 << (SPE - 1).bit_length(), seed=7)
        runner.run_epoch(complete_checkpoint=True)   # compile warmup
        for _ in range(2):
            runner.run_epoch(complete_checkpoint=True)
        device_sync(runner.executor.carry)
        prof = prof_mod.get_profiler()
        sections = {k: round(v * 1e3, 2)
                    for k, v in sorted(prof.lifetime().items())}
        out = {
            # Last closed epoch window — warm, the gauge /metrics serves.
            "overhead_ft_fraction": prof.ft_fraction(),
            # Whole probe incl. the compile-warmup epoch (upper bound).
            "overhead_ft_fraction_lifetime": round(
                prof.lifetime_ft_fraction(), 6),
            "sections_ms_lifetime": sections,
            "steps_per_epoch": SPE,
        }
        del runner
        gc.collect()
    finally:
        prof_mod.reset_profile()
    # Lineage on/off cost rides along (unprofiled — the dye plane's
    # cost is fence-side wall, not a profiler section).
    try:
        out["lineage"] = lineage_overhead_probe()
    except Exception as e:                            # pragma: no cover
        out["lineage"] = {"error": str(e)}
    return out


def lineage_overhead_probe():
    """Record-lineage cost at a bench shape (obs/lineage.py): the same
    short run twice — dye plane disabled (NullLineage: the identity,
    zero wire fields, zero per-record work, the fence never even
    extracts the epoch window for it) vs enabled (k records dyed per
    epoch, hops/determinants/sinks appended to a JSONL observation
    log at every fence). The disabled wall IS the baseline; the
    enabled-over-disabled fraction is the full price of answering
    \"explain this output record\" after the fact."""
    import gc
    import tempfile
    from clonos_tpu.obs.lineage import LineagePlane, NullLineage
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP

    SPE = int(os.environ.get("BENCH_LINEAGE_SPE", 256))
    EPOCHS = 3

    def timed(lin):
        job = build_job()
        need = 2 * SPE * DETS_PER_STEP
        runner = ClusterRunner(
            job, steps_per_epoch=SPE,
            log_capacity=1 << need.bit_length(), max_epochs=16,
            inflight_ring_steps=1 << (SPE - 1).bit_length(), seed=7,
            lineage=lin)
        runner.run_epoch(complete_checkpoint=True)   # compile warmup
        device_sync(runner.executor.carry)
        t0 = time.monotonic()
        for _ in range(EPOCHS):
            runner.run_epoch(complete_checkpoint=True)
        device_sync(runner.executor.carry)
        wall = time.monotonic() - t0
        del runner
        gc.collect()
        return wall

    off_s = timed(NullLineage())
    with tempfile.TemporaryDirectory() as td:
        lin = LineagePlane(td, service="bench", k=4)
        on_s = timed(lin)
        lin.close()
        dyed, n_obs = lin.dyed, lin.observations
    return {
        "lineage_off_s": round(off_s, 3),
        "lineage_on_s": round(on_s, 3),
        "lineage_overhead_fraction": (
            round(max(0.0, on_s / off_s - 1.0), 4) if off_s > 0
            else None),
        "records_dyed": dyed,
        "observations": n_obs,
        "steps_per_epoch": SPE,
        "epochs": EPOCHS,
    }


def ablation_probe():
    """FT-cost ablation (``bench.py --ablate``): the no-FT twin
    (analysis/ablate.py) head-to-head against the real executor on the
    same job, same seed, ``logical_time=True`` — so both see identical
    causal inputs and the twin's outputs are asserted bit-identical
    before its time is trusted. The wall delta is the *measured*
    ft-fraction; the census cost model (analysis/census.py) predicts a
    *static* ft-fraction from the same source; their relative error is
    the model's report card. The profiler's ``overhead.ft-fraction``
    gauge rides along as the third, runtime view (host-visible FT
    sections only — the in-block append cost is jitted away from it,
    so it lower-bounds the measured number)."""
    import gc
    import jax
    from clonos_tpu.analysis import (ablated_executor, build_census,
                                     static_cost_model)
    from clonos_tpu.analysis.census import _repo_contexts, fingerprint
    from clonos_tpu.obs import profile as prof_mod
    from clonos_tpu.runtime import executor as real_ex
    from clonos_tpu.runtime.executor import DETS_PER_STEP

    SPE = int(os.environ.get("BENCH_ABLATE_SPE", 512))
    EPOCHS = int(os.environ.get("BENCH_ABLATE_EPOCHS", 3))
    twin_mod, report = ablated_executor()

    def drive(ex_mod, profiled=False):
        job = build_job()
        need = (EPOCHS + 1) * SPE * DETS_PER_STEP
        ex = ex_mod.LocalExecutor(
            job, steps_per_epoch=SPE,
            log_capacity=1 << need.bit_length(), max_epochs=16,
            inflight_ring_steps=1 << (SPE - 1).bit_length(),
            block_steps=min(256, SPE), seed=7, logical_time=True)
        ex.run_epoch()                       # compile warmup
        device_sync(ex.carry)
        prof = prof_mod.get_profiler()
        t0 = time.monotonic()
        outs = None
        for _ in range(EPOCHS):
            if profiled:
                ft0 = sum(v for n, v in prof.lifetime().items())
                e0 = time.monotonic()
            outs = ex.run_epoch()
            device_sync(ex.carry)
            if profiled:
                # Attribute the epoch's non-FT wall as compute so the
                # gauge's rollup denominator is the full epoch.
                ft = sum(v for n, v in prof.lifetime().items()) - ft0
                wall = time.monotonic() - e0
                prof.observe("block-drive", max(wall - ft, 0.0),
                             kind=prof_mod.COMPUTE)
        wall_s = time.monotonic() - t0
        digest = (
            tuple(np.asarray(x) for x in
                  jax.tree_util.tree_leaves((ex.carry.op_states,
                                             ex.carry.edge_bufs,
                                             ex.carry.record_counts))),
            tuple(np.asarray(x) for x in
                  jax.tree_util.tree_leaves(outs.sinks)),
        )
        log_head = int(np.asarray(ex.carry.logs.head).max())
        rings = len(ex.carry.out_rings)
        subtasks = job.total_subtasks()
        del ex, job
        gc.collect()
        return wall_s, digest, log_head, rings, subtasks

    # Real run, profiled: the runtime gauge's view of the same epochs.
    prof_mod.configure_profile()
    try:
        t_real, d_real, head_real, rings, subtasks = drive(
            real_ex, profiled=True)
        prof = prof_mod.get_profiler()
        prof.rollup()
        gauge = prof.snapshot()
    finally:
        prof_mod.reset_profile()
    t_twin, d_twin, head_twin, _r, _s = drive(twin_mod)

    # Equivalence gate: the twin only measures FT cost if everything
    # BUT the logs is bit-identical.
    real_leaves = d_real[0] + d_real[1]
    twin_leaves = d_twin[0] + d_twin[1]
    identical = (len(real_leaves) == len(twin_leaves) and all(
        np.array_equal(a, b)
        for a, b in zip(real_leaves, twin_leaves)))
    if not identical:
        raise AssertionError(
            "ablation twin diverged from the real executor — the "
            "no-FT transform is not semantics-preserving for this "
            "job; refusing to report an ft-fraction")
    assert head_real > 0 and head_twin == 0, \
        (head_real, head_twin)

    measured = max(0.0, (t_real - t_twin) / t_real) if t_real else 0.0
    ctxs = _repo_contexts(("clonos_tpu", "examples"))
    census = build_census(ctxs)
    model = static_cost_model(
        census, steps_per_epoch=SPE, subtasks=subtasks,
        records_per_step=BATCH * PAR, ring_vertices=rings,
        record_touches=4)
    static = model["ft_fraction_static"]
    rel_err = abs(static - measured) / max(abs(measured), 1e-9)
    return {
        "ft_fraction_measured": round(measured, 6),
        "ft_fraction_static": static,
        "model_rel_error": round(rel_err, 6),
        "ft_fraction_gauge": gauge["lifetime_ft_fraction"],
        "t_real_s": round(t_real, 4),
        "t_twin_s": round(t_twin, 4),
        "epochs": EPOCHS,
        "steps_per_epoch": SPE,
        "subtasks": subtasks,
        "stripped_sites": len(report.stripped),
        "outputs_bit_identical": True,
        "log_rows_real": head_real,
        "log_rows_twin": head_twin,
        "static_model": model,
        "census_fingerprint": fingerprint(census),
    }


def multi_job_probe(n_jobs: int):
    """Multi-job throughput probe (``bench.py --jobs N`` /
    ``clonos_tpu bench --jobs N``): N independent small jobs sharing one
    device, stepped round-robin one epoch at a time — the in-process
    analog of the dispatcher's shared slot pool (runtime/dispatcher.py).
    Reports each job's sustained rate, the aggregate rate, and the
    min/max fairness ratio (1.0 = a perfectly fair interleave; the
    round-robin drive means any skew is runtime overhead, not
    scheduling bias)."""
    import gc
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP

    P, B = 2, 64
    SPE = int(os.environ.get("BENCH_JOBS_SPE", 256))
    EPOCHS = int(os.environ.get("BENCH_JOBS_EPOCHS", 4))
    runners = []
    for j in range(n_jobs):
        env = StreamEnvironment(name=f"bench-job{j}", num_key_groups=16,
                                default_edge_capacity=256)
        (env.synthetic_source(vocab=211, batch_size=B, parallelism=P)
            .key_by()
            .window_count(num_keys=211, window_size=1 << 30)
            .key_by()
            .reduce(num_keys=211)
            .sink())
        # Two epochs of log headroom: truncation lands at the NEXT
        # fence, so a ring sized to one epoch overflows mid-epoch.
        runners.append(ClusterRunner(
            env.build(), steps_per_epoch=SPE,
            log_capacity=1 << (2 * SPE * DETS_PER_STEP).bit_length(),
            max_epochs=EPOCHS + 4,
            inflight_ring_steps=1 << (2 * SPE - 1).bit_length(),
            seed=7 + j))
    for r in runners:                 # compile warmup, unmeasured
        r.run_epoch(complete_checkpoint=True)
        device_sync(r.executor.carry)
    walls = [0.0] * n_jobs
    t_all = time.monotonic()
    for _ in range(EPOCHS):
        for j, r in enumerate(runners):    # round-robin interleave
            t0 = time.monotonic()
            r.run_epoch(complete_checkpoint=True)
            device_sync(r.executor.carry)
            walls[j] += time.monotonic() - t0
    total_s = time.monotonic() - t_all
    records = EPOCHS * SPE * P * B
    rates = [round(records / w, 1) for w in walls]
    out = {
        "metric": "multi_job_aggregate_records_per_sec",
        "value": round(n_jobs * records / total_s, 1),
        "unit": "records/sec across all jobs",
        "jobs": n_jobs,
        "per_job_records_per_sec": rates,
        "fairness_min_over_max": round(min(rates) / max(rates), 3),
        "epochs_per_job": EPOCHS,
        "steps_per_epoch": SPE,
    }
    del runners
    gc.collect()
    return out


def multichip_probe(n_devices: int = 8):
    """Mesh-sharding probe (``bench.py --multichip [N]``): the SAME job
    run twice — once on a 1-device task mesh, once sharded over an
    N-device mesh (rule-driven PartitionSpec tree over carry, causal
    logs, and in-flight rings; parallel/distributed.py) — with the audit
    ledger sealing every epoch in both runs. Reports aggregate and
    per-shard steady-state throughput, the speedup and scaling
    efficiency, and whether the sharded run's sealed epoch digests are
    bit-identical to the unsharded run's (``diff_ledgers`` empty — the
    exactly-once fence contract is sharding-invariant).

    On a host with fewer than N devices the probe re-execs itself in a
    child forcing ``--xla_force_host_platform_device_count=N`` (the
    tests/conftest.py recipe), so it runs everywhere — including a
    single-CPU box, where the honest speedup is ~1x (virtual devices
    share one core; the digest-equality half is load-bearing there)."""
    import gc
    import subprocess
    import tempfile

    import jax

    if len(jax.devices()) < n_devices:
        env = dict(os.environ)
        kept = [f for f in env.get("XLA_FLAGS", "").split()
                if "xla_force_host_platform_device_count" not in f]
        kept.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(kept)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--multichip", str(n_devices)],
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"multichip child failed (rc={proc.returncode}):\n"
                f"{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    from clonos_tpu.obs.digest import diff_ledgers
    from clonos_tpu.parallel import distributed as dist
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP

    SPE = int(os.environ.get("BENCH_MC_SPE", 512))
    EPOCHS = int(os.environ.get("BENCH_MC_EPOCHS", 3))

    def run_one(ndev: int, ckdir: str):
        from clonos_tpu.api.environment import StreamEnvironment
        env = StreamEnvironment(name="bench-mc", num_key_groups=64,
                                default_edge_capacity=512)
        (env.synthetic_source(vocab=997, batch_size=BATCH, parallelism=PAR)
            .key_by()
            .window_count(num_keys=997, window_size=1 << 30, name="window")
            .key_by()
            .reduce(num_keys=997, name="reduce")
            .sink())
        runner = ClusterRunner(
            env.build(), steps_per_epoch=SPE,
            log_capacity=1 << (2 * SPE * DETS_PER_STEP).bit_length(),
            max_epochs=EPOCHS + 8,
            inflight_ring_steps=1 << (2 * SPE - 1).bit_length(),
            checkpoint_dir=ckdir, audit=True,
            mesh=dist.task_mesh(max_devices=ndev),
            logical_time=True, seed=7)
        runner.run_epoch(complete_checkpoint=True)   # compile warmup
        device_sync(runner.executor.carry)
        t0 = time.monotonic()
        for _ in range(EPOCHS):
            runner.run_epoch(complete_checkpoint=True)
        device_sync(runner.executor.carry)
        wall = time.monotonic() - t0
        shards = runner.per_shard_health()
        ledger = runner.coordinator.read_ledger()
        rate = EPOCHS * SPE * PAR * BATCH / wall
        rec_total = max(
            1, int(np.asarray(runner.executor.carry.record_counts).sum()))
        per_shard = None
        if shards is not None and ndev > 1:
            # Deal the aggregate rate out by each shard's actual record
            # share (the mesh partitions work, not just storage).
            per_shard = [round(rate * int(s) / rec_total, 1)
                         for s in np.asarray(shards)[:, 0]]
        del runner
        gc.collect()
        return rate, per_shard, ledger

    with tempfile.TemporaryDirectory() as td:
        rate_1, _ps1, ledger_1 = run_one(1, os.path.join(td, "m1"))
        rate_n, per_shard, ledger_n = run_one(n_devices,
                                              os.path.join(td, "mn"))
    problems = diff_ledgers(ledger_1, ledger_n)
    return {
        "metric": "multichip_aggregate_records_per_sec",
        "value": round(rate_n, 1),
        "unit": "records/sec (sharded over the task mesh)",
        "n_devices": n_devices,
        "records_per_sec_1dev": round(rate_1, 1),
        "records_per_sec_sharded": round(rate_n, 1),
        "per_shard_records_per_sec": per_shard,
        "speedup": round(rate_n / rate_1, 3) if rate_1 else None,
        "scaling_efficiency": (round(rate_n / rate_1 / n_devices, 3)
                               if rate_1 else None),
        "digests_equal": not problems,
        "ledger_problems": problems[:8],
        "epochs_sealed": min(len(ledger_1), len(ledger_n)),
        "steps_per_epoch": SPE,
    }


def soak_probe(duration_s: float = 30.0):
    """Open-loop soak probe (``bench.py --soak [SECONDS]``): every
    other number this file prints is closed-loop — the driver pushes
    epochs back-to-back and measures how fast they drain. This probe is
    the open-loop counterpart: a token bucket releases load at a fixed
    rate (``BENCH_SOAK_RATE`` records/sec) whether or not the cluster
    keeps up, a seeded chaos schedule injects a kill cascade, a gray
    failure, and a leader-lease loss mid-run, and latency is charged
    from each chunk's *intended*-send instant — the
    coordinated-omission-corrected view. The exactly-once audit ledger
    is re-diffed against a fault-free control twin after every fault;
    any divergence fails the probe."""
    import tempfile

    from clonos_tpu.soak import (ChaosSchedule, SLOSpec, SoakConfig,
                                 SoakDriver, build_soak_fixture,
                                 default_kill_targets)

    rate = float(os.environ.get("BENCH_SOAK_RATE", 2000))
    seed = int(os.environ.get("BENCH_SOAK_SEED", 11))
    with tempfile.TemporaryDirectory() as td:
        # The probe's runner pipelines its fence (the deployment
        # stance); the control twin inside the fixture stays
        # sequential, so every audit diff is overlapped-vs-sequential
        # and chaos kills can land mid-fence-tail.
        runner, control, election = build_soak_fixture(
            td, rate=rate, duration_s=duration_s, seed=seed,
            overlap_epoch=True)
        schedule = ChaosSchedule.seeded(
            seed, duration_s, default_kill_targets(runner.job))
        driver = SoakDriver(
            runner, SoakConfig(rate=rate, duration_s=duration_s),
            schedule=schedule, spec=SLOSpec(),
            control=control, election=election)
        v = driver.run()
    return {
        "metric": "soak_corrected_p99_ms",
        "value": v["latency"]["p99_ms"],
        "unit": "ms from intended-send (coordinated-omission-free)",
        "pass": v["pass"],
        "rate_target": v["rate_target"],
        "rate_achieved": v["rate_achieved"],
        "duration_s": v["duration_s"],
        "latency": v["latency"],
        "windows_breached": v["windows_breached"],
        "worst_window": v["worst_window"],
        "faults": v["faults"],
        "audit": v["audit"],
        "schedule": v["schedule"],
        "truncated": v["truncated"],
        "census_fingerprint": v.get("census_fingerprint"),
    }


def serve_probe(duration_s: float = 20.0):
    """Read-path probe (``bench.py --serve [SECONDS]``): prices the
    read tier (runtime/serve.py) honestly, one JSON line.

    1. **Batched vs sequential** at the headline 32-subtask shape: the
       same lookups issued as sequential point queries and as batched
       reads against a tailed replica (one coalesced jitted gather per
       device dispatch). The acceptance bar is >= 5x.
    2. **Bit-identity**: replica-served values vs owner-served values
       for the same keys at the same epoch stamp — must match exactly.
    3. **Mixed load + degradation**: the soak driver pumps routed reads
       between ingest chunks with read-latency SLO windows, and a
       ``replica-kill`` chaos event mid-run must degrade (re-route to
       owner, staleness spike then recovery) with ZERO client-visible
       errors, audit still clean."""
    import gc
    import tempfile

    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP
    from clonos_tpu.runtime.serve import build_serve_tier
    from clonos_tpu.soak import (ServeLoad, SLOSpec, SoakConfig,
                                 SoakDriver, build_soak_fixture,
                                 parse_schedule)

    def reduce_vid(job):
        return next(v.vertex_id for v in job.vertices
                    if getattr(v.operator, "emits_running_value", False))

    # -- part 1+2: batched vs sequential + bit-identity, 32 subtasks --
    SPE = int(os.environ.get("BENCH_SERVE_SPE", 256))
    N_SEQ = int(os.environ.get("BENCH_SERVE_SEQ_READS", 256))
    N_BATCH = int(os.environ.get("BENCH_SERVE_BATCH_READS", 4096))
    CHUNK = 256
    job = build_job()
    vid = reduce_vid(job)
    runner = ClusterRunner(
        job, steps_per_epoch=SPE,
        log_capacity=1 << (2 * SPE * DETS_PER_STEP).bit_length(),
        max_epochs=16,
        inflight_ring_steps=1 << (2 * SPE - 1).bit_length(),
        block_steps=min(256, SPE), seed=7)
    # tier FIRST: replicas subscribe to the serve feed before any epoch
    # seals, so they tail every fence from the start
    tier = build_serve_tier(runner, vid, n_replicas=2)
    for _ in range(3):
        runner.run_epoch(complete_checkpoint=True)
    runner.drain_fence()
    rng = np.random.RandomState(13)
    rep = tier.clients[0]
    # warm the gather compile off the measured clock
    rep.query_batch(vid, [0])
    rep.query(vid, 0)
    keys_seq = rng.randint(0, 997, N_SEQ)
    t0 = time.monotonic()
    seq_out = [rep.query(vid, int(k)) for k in keys_seq]
    seq_s = time.monotonic() - t0
    keys_b = rng.randint(0, 997, N_BATCH)
    t0 = time.monotonic()
    batch_epochs = []
    batch_vals = {}
    for i in range(0, N_BATCH, CHUNK):
        chunk = [int(k) for k in keys_b[i:i + CHUNK]]
        out = rep.query_batch(vid, chunk)
        batch_epochs.append(out["epoch"])
        batch_vals.update(zip(chunk, out["values"]))
    batch_s = time.monotonic() - t0
    qps_seq = N_SEQ / seq_s if seq_s else 0.0
    qps_batch = N_BATCH / batch_s if batch_s else 0.0
    speedup = qps_batch / qps_seq if qps_seq else 0.0
    # bit-identity vs the owner at the same epoch stamp
    probe_keys = sorted(batch_vals)
    own = tier.owner_client.query_batch(vid, probe_keys)
    same_epoch = (own["epoch"] == batch_epochs[-1]
                  and all(e == own["epoch"] for e in batch_epochs))
    mismatches = [int(k) for k, ov in zip(probe_keys, own["values"])
                  if batch_vals[k] != ov]
    # point reads must agree with batched reads too
    point_ok = all(o["value"] == batch_vals.get(int(k), o["value"])
                   for k, o in zip(keys_seq, seq_out))
    replica_status = [r.status() for r in tier.replicas]
    dispatches = [ep.dispatches for ep in tier.endpoints]
    keys_served = [ep.keys_served for ep in tier.endpoints]
    tier.close()
    del runner, job
    gc.collect()

    # -- part 3: mixed read/ingest load with a replica-kill mid-run --
    rate = float(os.environ.get("BENCH_SERVE_RATE", 2000))
    slo_ms = float(os.environ.get("BENCH_SERVE_SLO_MS", 2000))
    with tempfile.TemporaryDirectory() as td:
        srun, control, election = build_soak_fixture(
            td, rate=rate, duration_s=duration_s, seed=11,
            overlap_epoch=True, serve_vertex=True)
        svid = reduce_vid(srun.job)
        stier = build_serve_tier(srun, svid, n_replicas=2,
                                 staleness_bound=2)
        load = ServeLoad(stier, svid, num_keys=101,
                         reads_per_pump=32, slo_ms=slo_ms)
        kill_at = round(0.4 * duration_s, 1)
        schedule = parse_schedule(f"at {kill_at}s replica-kill 0")
        driver = SoakDriver(
            srun, SoakConfig(rate=rate, duration_s=duration_s),
            schedule=schedule, spec=SLOSpec(),
            control=control, election=election, read_load=load)
        v = driver.run()
        stier.close()
    serve = v["serve"]
    audit_ok = bool(v["audit"]["exactly_once"])
    degraded_not_failed = (serve["errors"] == 0
                           and serve["reroutes"] > 0
                           and serve["staleness_peak"]
                           > serve["staleness_final"])

    return {
        "metric": "serve_batched_read_speedup",
        "value": round(speedup, 2),
        "unit": "batched replica reads vs sequential point queries "
                "(same keys, 32-subtask shape)",
        "pass": bool(speedup >= 5.0 and same_epoch and not mismatches
                     and point_ok and serve["ok"]
                     and degraded_not_failed and audit_ok),
        "read_qps_sequential": round(qps_seq, 1),
        "read_qps_batched": round(qps_batch, 1),
        "sequential_reads": N_SEQ,
        "batched_reads": N_BATCH,
        "batch_chunk": CHUNK,
        "device_dispatches": dispatches,
        "keys_served": keys_served,
        "bit_identical_vs_owner": not mismatches,
        "bit_identity_keys_checked": len(probe_keys),
        "bit_identity_mismatched_keys": mismatches[:8],
        "same_epoch_stamp": same_epoch,
        "point_vs_batch_consistent": point_ok,
        "replica_status": replica_status,
        "mixed_load": {
            "ingest_rate_target": v["rate_target"],
            "ingest_rate_achieved": v["rate_achieved"],
            "ingest_p99_ms": v["latency"]["p99_ms"],
            "serve": serve,
            "degraded_not_failed": degraded_not_failed,
            "audit": v["audit"],
            "schedule": v["schedule"],
        },
        "census_fingerprint": v.get("census_fingerprint"),
    }


def rescale_probe(duration_s: float = 12.0):
    """Elastic-repartition probe (``bench.py --rescale [SECONDS]``):
    prices a live 2->4 re-cut at a checkpoint fence, one JSON line.

    Throughput is sampled before and after the re-cut under the same
    epoch cadence, and the handoff itself is timed — the fence stall a
    paced client would see: drain + keyed-state migration + the
    new-shape restore point (the new incarnation's first-epoch compile
    is reported separately; it overlaps the stall only on a multi-core
    host). On a 1-core CI host doubling the keyed cut cannot raise
    throughput, so the honest acceptance bar is the exactly-once
    evidence, not a throughput win: the protocol transitions observed
    in fence -> drain -> migrate -> redirect order, every in-flight
    record drained and re-routed, the fenced-off incarnation refusing
    to run, and the post-re-cut ledger diffing EMPTY against a
    never-rescaled control via the key-group directory
    (obs/audit.diff_ledgers_cross) while the exact byte diff refuses —
    proof the mapped cross-layout path engaged, not a trivial pass."""
    import tempfile

    from clonos_tpu.causal import recovery as rec
    from clonos_tpu.obs import audit as audit_mod
    from clonos_tpu.obs.digest import diff_ledgers
    from clonos_tpu.soak import build_soak_fixture

    SPE = int(os.environ.get("BENCH_RESCALE_SPE", 32))
    EPOCHS = int(os.environ.get("BENCH_RESCALE_EPOCHS", 4))
    TARGET = int(os.environ.get("BENCH_RESCALE_TARGET", 4))
    PAR, BATCH = 2, 8                     # build_soak_fixture defaults
    per_epoch = SPE * PAR * BATCH
    with tempfile.TemporaryDirectory() as td:
        runner, control, _election = build_soak_fixture(
            td, rate=2000.0, duration_s=duration_s,
            steps_per_epoch=SPE, par=PAR, batch=BATCH, seed=11)
        # warm both epoch programs off the measured clock
        runner.run_epoch(complete_checkpoint=True)
        control.run_epoch(complete_checkpoint=True)
        runner.drain_fence()

        t0 = time.monotonic()
        for _ in range(EPOCHS):
            runner.run_epoch(complete_checkpoint=True)
        runner.drain_fence()
        before_s = time.monotonic() - t0

        # the live re-cut: everything between the old incarnation's
        # last fence and the new one being runnable is fence stall
        t0 = time.monotonic()
        new_runner, stats = runner._soak_rescaler(TARGET)
        stall_s = time.monotonic() - t0

        t0 = time.monotonic()
        new_runner.run_epoch(complete_checkpoint=True)
        new_runner.drain_fence()
        first_epoch_s = time.monotonic() - t0   # compile-dominated

        t0 = time.monotonic()
        for _ in range(EPOCHS):
            new_runner.run_epoch(complete_checkpoint=True)
        new_runner.drain_fence()
        after_s = time.monotonic() - t0

        # the fenced-off incarnation must refuse to double-apply
        stale_fenced = False
        try:
            runner.run_epoch()
        except rec.RecoveryError:
            stale_fenced = True

        # never-rescaled control reaches the same sealed epoch; the
        # cross-layout diff must be clean AND the exact diff must not
        # be (same mapping `clonos_tpu audit A --diff B` uses)
        while control.auditor.last_epoch < new_runner.auditor.last_epoch:
            control.run_epoch(complete_checkpoint=True)
        control.drain_fence()
        hi = new_runner.auditor.last_epoch
        expected = [e for e in control.auditor.ledger()
                    if e["epoch"] <= hi]
        actual = [e for e in new_runner.auditor.ledger()
                  if e["epoch"] <= hi]
        cross = audit_mod.diff_ledgers_cross(expected, actual)
        exact = diff_ledgers(expected, actual)

    kinds = [k for k, _ in stats["transitions"]]
    first = {k: kinds.index(k) for k in dict.fromkeys(kinds)}
    proto_ok = ("fence" in first and "migrate" in first
                and kinds[-1] == "redirect"
                and first["fence"] < first["migrate"]
                and kinds.count("migrate") == stats["groups"]
                and ("drain" not in first
                     or first["drain"] > first["fence"]))
    moved = stats["moved_key_groups"]
    stall_ms = stall_s * 1e3
    passed = bool(cross == [] and exact and stale_fenced and proto_ok
                  and moved and all(m > 0 for m in moved.values())
                  and hi > stats["fence_checkpoint"])
    out = {
        "metric": "rescale_live_recut",
        "value": round(stall_ms, 1),
        "unit": f"ms fence stall for a live {PAR}->{TARGET} keyed "
                f"re-cut (drain + migrate + new-shape restore point)",
        "pass": passed,
        "target_parallelism": TARGET,
        "steps_per_epoch": SPE,
        "epochs_each_side": EPOCHS,
        "throughput_before": round(EPOCHS * per_epoch / before_s, 1),
        "throughput_after": round(EPOCHS * per_epoch / after_s, 1),
        "fence_stall_ms": round(stall_ms, 1),
        "migrate_ms": round(stats["migrate_ms"], 1),
        "post_recut_first_epoch_ms": round(first_epoch_s * 1e3, 1),
        "drained_records": stats["drained_records"],
        "moved_key_groups": moved,
        "protocol_groups": stats["groups"],
        "transitions": kinds,
        "protocol_order_ok": proto_ok,
        "stale_writer_fenced": stale_fenced,
        "cross_ledger_diff_clean": cross == [],
        "cross_ledger_diff": cross[:4],
        "exact_diff_refuses": bool(exact),
        "exact_diff_lines": len(exact),
        "epochs_checked": len(actual),
        "note": "single-host CI shape: throughput_before/after share "
                "one core, so the re-cut prices the protocol (stall + "
                "exactly-once evidence), not a scaling win",
    }
    try:
        from clonos_tpu.analysis import census_fingerprint
        out["census_fingerprint"] = census_fingerprint()
    except Exception:                                 # pragma: no cover
        out["census_fingerprint"] = None
    return out


def spill_probe():
    """Tiered-storage probe (``bench.py --spill``): prices the spill
    fabric (clonos_tpu/storage/) three ways, one JSON line.

    1. **Steady state**: the same job three ways — spill OFF, spill ON
       under the ``availability`` policy (the production steady state:
       checkpoints complete every epoch, the ring keeps headroom, ring
       payloads stay put and only the small determinant windows move),
       and spill ON ``eager`` (the upper bound: every in-flight byte
       made durable every epoch). The 5% acceptance bound is
       availability vs off; eager is reported alongside — on a
       many-core host its writer thread overlaps compute, on this
       box's core count it shows up as foreground cost.
    2. **Deep backlog**: pending epochs accumulate until the replay
       span EXCEEDS device ring capacity, then a kill — recovery must
       refill the missing leading steps from the host/disk tiers.
       Timed, and verified bit-identical: the audit ledger diffs empty
       against a no-spill control run whose ring holds the whole span
       (``diff_ledgers == []``).
    3. **Tiers**: occupancy at the moment of the kill plus cumulative
       movement counters (the ``spill.*`` gauges' source), emitted as
       BENCH_r0N.json fields.
    """
    import gc
    import tempfile

    from clonos_tpu.obs.digest import diff_ledgers
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP

    SPE = int(os.environ.get("BENCH_SPILL_SPE", 512))
    EPOCHS = int(os.environ.get("BENCH_SPILL_EPOCHS", 3))
    FILL = int(os.environ.get("BENCH_SPILL_FILL_EPOCHS", 4))

    def steady(spool_dir, policy=None):
        job = build_job()
        need = (EPOCHS + 2) * SPE * DETS_PER_STEP
        # Ring holds 4 epochs so the availability policy has headroom:
        # with checkpoints completing every epoch, occupancy stays at
        # ~0.25 < the 0.3 trigger and nothing needs to move — the
        # production steady state. Same ring for every mode (eager's
        # cost is ring-size independent) so the comparison is fair.
        kw = dict(steps_per_epoch=SPE,
                  log_capacity=1 << need.bit_length(), max_epochs=16,
                  inflight_ring_steps=1 << (4 * SPE - 1).bit_length(),
                  block_steps=min(1024, SPE), seed=7)
        if spool_dir:
            kw["spool_dir"] = spool_dir
            kw["spill_policy"] = policy
        runner = ClusterRunner(job, **kw)
        runner.run_epoch(complete_checkpoint=True)    # compile warmup
        device_sync(runner.executor.carry)
        t0 = time.monotonic()
        for _ in range(EPOCHS):                       # pipelined
            runner.run_epoch(complete_checkpoint=True)
        device_sync(runner.executor.carry)
        wall = time.monotonic() - t0
        drain_s = 0.0
        if spool_dir:
            # The writer thread overlaps compute; what's LEFT in its
            # queue at the fence is the true async residue — timed
            # separately so steady state measures overlap, not total
            # spill bandwidth.
            t1 = time.monotonic()
            runner.executor.drain_spill()
            drain_s = time.monotonic() - t1
        rate = EPOCHS * SPE * PAR * BATCH / wall if wall else 0.0
        stats = dict(runner.executor.spill_stats()) if spool_dir else {}
        if spool_dir:
            stats["drain_residue_ms"] = round(drain_s * 1e3, 1)
        del runner, job
        gc.collect()
        return rate, stats

    def backlog_run(spool_dir, ring_steps, budget):
        job = build_job()
        need = (FILL + 2) * SPE * DETS_PER_STEP
        kw = dict(steps_per_epoch=SPE,
                  log_capacity=1 << need.bit_length(), max_epochs=16,
                  inflight_ring_steps=ring_steps,
                  block_steps=min(1024, SPE), seed=7,
                  logical_time=True, audit=True)
        if spool_dir:
            kw["spool_dir"] = spool_dir
            kw["spill_host_budget_epochs"] = budget
        runner = ClusterRunner(job, **kw)
        runner.run_epoch(complete_checkpoint=True)    # restore point
        for _ in range(FILL):                         # pending backlog
            runner.run_epoch(complete_checkpoint=False)
        device_sync(runner.executor.carry)
        return runner

    with tempfile.TemporaryDirectory() as td:
        rate_avail, avail_stats = steady(os.path.join(td, "a"),
                                         "availability")
    with tempfile.TemporaryDirectory() as td:
        rate_eager, eager_stats = steady(os.path.join(td, "e"), "eager")
    rate_off, _ = steady(None)
    overhead = ((rate_off - rate_avail) / rate_off) if rate_off else 0.0
    eager_overhead = ((rate_off - rate_eager) / rate_off
                      if rate_off else 0.0)

    # Deep backlog: the spill run's ring holds ONE epoch, the replay
    # span is FILL of them; host budget 1 forces most epochs disk-only.
    with tempfile.TemporaryDirectory() as td:
        r = backlog_run(os.path.join(td, "spill"),
                        ring_steps=1 << (SPE - 1).bit_length(), budget=1)
        r.executor.drain_spill()
        occupancy = r.executor.spill_occupancy()
        r.inject_failure([PAR + 1])                   # window subtask 1
        t0 = time.monotonic()
        report = r.recover()
        device_sync(r.executor.carry)
        backlog_recovery_ms = (time.monotonic() - t0) * 1e3
        move_stats = r.executor.spill_stats()
        ledger_spill = list(r.auditor.ledger())
        steps_replayed = report.steps_replayed
        ring_cap = 1 << (SPE - 1).bit_length()
        del r
        gc.collect()
    control = backlog_run(None,
                          ring_steps=1 << (FILL * SPE).bit_length(),
                          budget=0)
    ledger_ctrl = list(control.auditor.ledger())
    del control
    gc.collect()
    problems = diff_ledgers(ledger_ctrl, ledger_spill)

    return {
        "metric": "spill_throughput_overhead_fraction",
        "value": round(overhead, 6),
        "unit": "1 - rate(spill availability)/rate(spill off), steady "
                "state; eager upper bound reported alongside",
        "pass": bool(overhead <= 0.05 and not problems
                     and steps_replayed > ring_cap
                     and move_stats.get("disk_hits", 0) > 0),
        "steady_state_records_per_sec_spill_availability":
            round(rate_avail, 1),
        "steady_state_records_per_sec_spill_eager": round(rate_eager, 1),
        "steady_state_records_per_sec_spill_off": round(rate_off, 1),
        "eager_overhead_fraction": round(eager_overhead, 6),
        "steady_spill_stats": {"availability": avail_stats,
                               "eager": eager_stats},
        "backlog_recovery_ms": round(backlog_recovery_ms, 1),
        "backlog_steps_replayed": steps_replayed,
        "backlog_ring_capacity_steps": ring_cap,
        "backlog_exceeds_ring": bool(steps_replayed > ring_cap),
        "tier_occupancy_at_kill": occupancy,
        "spill_movement": move_stats,
        "digests_equal": not problems,
        "ledger_diff": problems[:8],
        "steps_per_epoch": SPE,
        "fill_epochs": FILL,
    }


def main(jobs=None, multichip=None, soak=None, ablate=False,
         spill=False, serve=None, rescale=None, overhead=False):
    global T_START
    if overhead:
        # --overhead: run ONLY the FT-overhead attribution probe (the
        # profiled section breakdown + the lineage on/off cost) — the
        # standalone escape hatch so a budget-starved headline run
        # never leaves the overhead numbers unmeasured.
        T_START = time.monotonic()
        print(json.dumps(overhead_probe()))
        return
    if rescale:
        # --rescale [SECONDS]: run ONLY the elastic-repartition probe
        # (one JSON line, same contract as the headline bench) and
        # persist it as the next free RESCALE_r0N.json artifact.
        from clonos_tpu.soak import next_rescale_artifact_path
        out = rescale_probe(float(rescale))
        path = next_rescale_artifact_path()
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        out["artifact"] = os.path.basename(path)
        print(json.dumps(out))
        return 0 if out["pass"] else 1
    if serve:
        # --serve [SECONDS]: run ONLY the read-path probe (one JSON
        # line, same contract as the headline bench) and persist it as
        # the next free SERVE_r0N.json artifact.
        from clonos_tpu.soak import next_serve_artifact_path
        out = serve_probe(float(serve))
        path = next_serve_artifact_path()
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        out["artifact"] = os.path.basename(path)
        print(json.dumps(out))
        return 0 if out["pass"] else 1
    if spill:
        # --spill: run ONLY the tiered-storage probe (one JSON line,
        # same contract as the headline bench).
        print(json.dumps(spill_probe()))
        return
    if ablate:
        # --ablate: run ONLY the no-FT ablation probe (one JSON line,
        # same contract as the headline bench).
        print(json.dumps(ablation_probe()))
        return
    if soak:
        # --soak [SECONDS]: run ONLY the open-loop soak probe (one JSON
        # line, same contract as the headline bench).
        print(json.dumps(soak_probe(float(soak))))
        return
    if multichip:
        # --multichip [N]: run ONLY the mesh-sharding probe (one JSON
        # line, same contract as the headline bench).
        print(json.dumps(multichip_probe(int(multichip))))
        return
    if jobs:
        # --jobs N: run ONLY the multi-job probe (one JSON line, same
        # contract as the headline bench).
        print(json.dumps(multi_job_probe(int(jobs))))
        return

    import jax
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP
    from clonos_tpu.causal import recovery as rec

    T_START = time.monotonic()
    job = build_job()
    # Log capacity sized to hold FILL_EPOCHS * STEPS_PER_EPOCH * 4 sync
    # rows plus control-plane determinants (SOURCE_CHECKPOINT per trigger).
    need = FILL_EPOCHS * STEPS_PER_EPOCH * DETS_PER_STEP
    cap = 1 << need.bit_length()
    # Ring sized to EXACTLY the fill span (power of two): doubling the
    # backlog must not double HBM — the ring holds precisely the
    # un-truncated window recovery can need.
    span = max(FILL_EPOCHS * STEPS_PER_EPOCH, 2)
    # Persistent compile cache (utils/compile_cache.py): the prewarmed
    # recovery programs + AOT first-step executable survive process
    # restarts, so a re-run of this bench (and a restarted standby in
    # deployment) pays near-zero prewarm compile. Opt out with
    # BENCH_COMPILE_CACHE="".
    cache_dir = os.environ.get(
        "BENCH_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    # The headline runs the PIPELINED fence (overlap_epoch=True): each
    # epoch's seal/ledger/checkpoint tail executes on the fence worker
    # while the next epoch's compute is already dispatched. The
    # sequential control below re-measures the same schedule with the
    # tail on the critical path.
    # max_epochs=32: the headline schedule (warm + 3+4 measured), the
    # same-runner sequential control (3+4), and the A-B-A overlap
    # re-measurement (3+4) stack to epoch 22 in ONE runner — per-epoch
    # index vectors are 4 bytes/epoch/log, so the headroom is free.
    runner = ClusterRunner(job, steps_per_epoch=STEPS_PER_EPOCH,
                           log_capacity=cap, max_epochs=32,
                           inflight_ring_steps=1 << (span - 1).bit_length(),
                           recovery_block_steps=8192,
                           block_steps=1024,
                           latency_marker_every=64,
                           seed=7,
                           overlap_epoch=True,
                           compile_cache_dir=cache_dir or None)

    t_warm0 = time.monotonic()
    runner.run_epoch(complete_checkpoint=True)    # epoch 0: restore point
    device_sync(runner.executor.carry)
    warm_epoch_s = time.monotonic() - t_warm0

    # Warm standby: deploy (= compile) the recovery programs up front, the
    # analog of the reference keeping standby tasks deployed and
    # state-refreshed (RunStandbyTaskStrategy). Off the failure path.
    prewarm_s = runner.prewarm_recovery()

    # Steady state is measured over PIPELINED epoch windows — no device
    # sync between epochs (a real deployment never round-trips the
    # tunnel per fence; one d2h sync costs ~110ms here). The reported
    # rate is the SUSTAINED aggregate across all 3+FILL_EPOCHS epochs
    # (total records / total wall, drill excluded) — transient tunnel
    # stalls average in rather than being cherry-picked around.
    run_s = 0.0
    # Fence walls (global_step, monotonic_s) at each measured epoch's
    # dispatch return: the schedule anchor coordinated-omission
    # correction needs — a fence that blocked late makes every marker
    # sample in its epoch late too, which the markers alone never show.
    fence_walls = []
    t_w = time.monotonic()
    for i in range(3):                # completed epochs: logs truncate
        runner.run_epoch(complete_checkpoint=True)
        fence_walls.append((runner.global_step, time.monotonic()))
    device_sync(runner.executor.carry)
    run_s += time.monotonic() - t_w
    # Failover drill (standby rehearsal): one full multi-class recovery
    # with real replay work, leaving state bit-identical. After this the
    # first REAL failure pays no first-execution warmup — the
    # RunStandbyTaskStrategy "standbys run hot" capability, measured
    # below as recovery_time_cold_ms. (Run mid-data: after the first
    # fill epoch there are steps to replay.)
    t_w = time.monotonic()
    runner.run_epoch(complete_checkpoint=False)
    fence_walls.append((runner.global_step, time.monotonic()))
    device_sync(runner.executor.carry)
    run_s += time.monotonic() - t_w
    drill_s = runner.failover_drill()
    device_sync(runner.executor.carry)
    t_w = time.monotonic()
    for _ in range(FILL_EPOCHS - 1):
        runner.run_epoch(complete_checkpoint=False)
        fence_walls.append((runner.global_step, time.monotonic()))
    device_sync(runner.executor.carry)
    run_s += time.monotonic() - t_w
    throughput = ((3 + FILL_EPOCHS) * STEPS_PER_EPOCH * PAR * BATCH
                  / run_s)

    buffered = int(np.sum(runner.executor.log_sizes()))

    # Sequential control: the SAME runner, back-to-back, re-measured
    # over the identical epoch schedule with per-call
    # overlap_fence=False — the strict-order fence tail (health read,
    # snapshot trigger, source append) on the critical path. Same
    # process, same warm state, same memory: the only variable is the
    # fence mode. headline / control is the pipelined fence's
    # steady-state delta; the control never writes fence.overlap-saved.
    runner.drain_fence()    # join the last overlapped tail off-clock
    # Off-clock ring reset: the fill epochs left the ring exactly full
    # (that's the point — recovery below replays them), so the control
    # epochs would overflow it. Completing the NEWEST pending fence
    # truncates the ring through it without running a single step;
    # older pendings are discarded first (completing them late would
    # regress the truncation watermark — same barrier the soak driver
    # uses pre-kill).
    runner.coordinator.drain()            # async snapshot writes durable
    last_fence = runner.executor.epoch_id - 1
    runner.coordinator.discard_pending_through(last_fence - 1)
    runner.coordinator.ack_all(last_fence)
    device_sync(runner.executor.carry)
    t_c = time.monotonic()
    for _ in range(3):
        runner.run_epoch(complete_checkpoint=True, overlap_fence=False)
    for _ in range(FILL_EPOCHS):
        runner.run_epoch(complete_checkpoint=False, overlap_fence=False)
    device_sync(runner.executor.carry)
    ctrl_s = time.monotonic() - t_c
    throughput_ctrl = ((3 + FILL_EPOCHS) * STEPS_PER_EPOCH * PAR * BATCH
                       / ctrl_s)
    assert "fence.overlap-saved" not in runner.last_fence_phases, \
        "sequential control must never write the overlap key"

    # A-B-A: re-measure the PIPELINED mode after the control. On this
    # host a ~20-minute single-core process drifts run-to-run by more
    # than the fence tail costs, and whichever mode runs later measures
    # warmer — comparing A2 against the control (adjacent windows)
    # bounds that bias in the artifact itself instead of pretending the
    # first A and B were exchangeable.
    budget_s = float(os.environ.get("BENCH_MAX_S", 1500))
    # The tail of the budget is RESERVED for the overhead probe:
    # BENCH_r06 let the secondary configs eat the whole budget and the
    # probe starved ({"skipped": ...}). Everything optional before the
    # probe now stops at soft_budget_s so the probe always gets its
    # slice; `bench.py --overhead` runs it standalone besides.
    overhead_reserve_s = float(
        os.environ.get("BENCH_OVERHEAD_RESERVE_S", 180))
    soft_budget_s = max(0.0, budget_s - overhead_reserve_s)
    throughput_rerun = None
    if time.monotonic() - T_START <= soft_budget_s:
        runner.coordinator.drain()
        last_fence = runner.executor.epoch_id - 1
        runner.coordinator.discard_pending_through(last_fence - 1)
        runner.coordinator.ack_all(last_fence)
        device_sync(runner.executor.carry)
        t_r = time.monotonic()
        for _ in range(3):
            runner.run_epoch(complete_checkpoint=True)
        for _ in range(FILL_EPOCHS):
            runner.run_epoch(complete_checkpoint=False)
        device_sync(runner.executor.carry)
        throughput_rerun = ((3 + FILL_EPOCHS) * STEPS_PER_EPOCH * PAR
                            * BATCH / (time.monotonic() - t_r))
        runner.drain_fence()   # join the last tail before the kill below

    failed_flat = PAR + 1     # window vertex, subtask 1
    runner.inject_failure([failed_flat])
    t0 = time.monotonic()
    report = runner.recover()
    device_sync(runner.executor.carry)
    cold_recovery_s = time.monotonic() - t0

    # Recovery-time-to-resume, steady state: fail the same subtask again —
    # the full protocol (determinant fetch, input reconstruction, replay,
    # verify, patch, replica rebuild) on prewarmed programs. Min sheds
    # tunnel-latency noise; the mean is reported alongside (the honest
    # number a noisy link delivers). Phases and the headline come from
    # the SAME run and statistic: the best run's own report feeds
    # recovery_phase_ms (BENCH_r05 mixed the cold run's breakdown with
    # the warm minimum, so sub-phases summed past the headline).
    warm_runs = []                                # (seconds, report)
    for _ in range(3):
        runner.inject_failure([failed_flat])
        t2 = time.monotonic()
        rep_w = runner.recover()
        device_sync(runner.executor.carry)
        warm_runs.append((time.monotonic() - t2, rep_w))
    warm_recovery_s, warm_report = min(warm_runs, key=lambda sr: sr[0])
    warm_recovery_runs = [s for s, _ in warm_runs]

    # Bit-identity of the overlapped pipeline vs a strictly sequential
    # control: digest the live state over the replayed window after the
    # overlapped runs, recover the same failure once more with
    # overlap_finalize=False (the pre-PR12 ordering), digest again, and
    # diff. An empty diff says the overlap changed WHEN finalize work
    # ran, not WHAT state the job resumed on.
    from clonos_tpu.causal.recovery import AuditValidator
    from clonos_tpu.obs.digest import diff_ledgers
    audit_epochs = list(range(warm_report.from_epoch,
                              runner.executor.epoch_id))
    _val = AuditValidator(runner.executor, [])
    entries_overlap = _val.recompute_entries(audit_epochs)
    runner.inject_failure([failed_flat])
    t2 = time.monotonic()
    runner.recover(overlap_finalize=False)
    device_sync(runner.executor.carry)
    seq_recovery_s = time.monotonic() - t2
    entries_seq = _val.recompute_entries(audit_epochs)
    ledger_diff = diff_ledgers(entries_seq, entries_overlap)

    # Warm replay rate: re-run the device replay on the same plan (the cold
    # number includes XLA compilation of the replay scan; steady-state
    # recovery of subsequent failures reuses the compiled program).
    mgr = report.managers[0]
    replayer = mgr.replayer
    warm_replay_runs = []
    for _ in range(5):
        t1 = time.monotonic()
        result = replayer.replay(mgr.plan)
        device_sync(result.emit_counts)
        warm_replay_runs.append(time.monotonic() - t1)
    warm_replay_s = min(warm_replay_runs)

    records_per_sec = (report.records_replayed / warm_replay_s
                       if warm_replay_s > 0 else 0.0)
    dets_per_sec = (report.steps_replayed * DETS_PER_STEP / warm_replay_s
                    if warm_replay_s > 0 else 0.0)

    out = {
        "metric": "recovery_replay_records_per_sec",
        "value": round(records_per_sec, 1),
        "unit": "records/sec (~= JVM determinants/sec)",
        "vs_baseline": round(records_per_sec / JVM_BASELINE_RECORDS_PER_SEC,
                             3),
        "replay_determinant_rows_per_sec": round(dets_per_sec, 1),
        "recovery_time_cold_ms": round(cold_recovery_s * 1e3, 1),
        "recovery_time_warm_ms": round(warm_recovery_s * 1e3, 1),
        "recovery_time_warm_mean_ms": round(
            1e3 * sum(warm_recovery_runs) / len(warm_recovery_runs), 1),
        "recovery_time_warm_sequential_ms": round(seq_recovery_s * 1e3, 1),
        # diff_ledgers(sequential-control digests, overlapped digests)
        # over the replayed epoch window — [] proves the overlapped
        # recovery left bit-identical state.
        "ledger_diff_vs_sequential_control": ledger_diff,
        "prewarm_standby_s": round(prewarm_s, 1),
        "failover_drill_s": round(drill_s, 1),
        "replay_time_warm_ms": round(warm_replay_s * 1e3, 1),
        "replay_time_warm_mean_ms": round(
            1e3 * sum(warm_replay_runs) / len(warm_replay_runs), 1),
        "vs_baseline_mean": round(
            report.records_replayed
            / (sum(warm_replay_runs) / len(warm_replay_runs))
            / JVM_BASELINE_RECORDS_PER_SEC, 3),
        # Same-run statistic: the BEST warm run's own breakdown (its
        # values sum to ~recovery_time_warm_ms), the per-phase mean
        # across all warm runs, and the cold run's breakdown under its
        # own explicitly-cold key.
        "recovery_phase_ms": {k: round(v, 1)
                              for k, v in warm_report.phase_ms.items()},
        "recovery_phase_mean_ms": {
            k: round(sum(r.phase_ms.get(k, 0.0) for _s, r in warm_runs)
                     / len(warm_runs), 1)
            for k in sorted({k for _s, r in warm_runs for k in r.phase_ms})},
        "recovery_phase_cold_ms": {k: round(v, 1)
                                   for k, v in report.phase_ms.items()},
        # The finalize mystery, attributable: named sub-spans of the
        # finalize phase (barrier read, state verify, and — on standby
        # bootstraps — rehydrate/reattach/reregister/recompile), plus
        # finalize.overlap-saved: wall time the overlapped tail removed
        # from the critical path (sum(sub-spans) - saved == finalize).
        "finalize_phase_ms": {k: round(v, 1)
                              for k, v in warm_report.phase_ms.items()
                              if k == "finalize"
                              or k.startswith("finalize.")},
        "finalize_overlap_saved_ms": round(
            warm_report.phase_ms.get("finalize.overlap-saved", 0.0), 1),
        "steps_replayed": report.steps_replayed,
        "records_replayed": report.records_replayed,
        "buffered_determinants_cluster": buffered,
        "steady_state_records_per_sec": round(throughput, 1),
        # Cumulative wall time the pipelined fence removed from the
        # critical path across every overlapped epoch above:
        # sum over epochs of max(0, sum(fence.* sub-spans) - joined
        # tail wall). The per-epoch identity
        # sum(fence.*) - overlap-saved == fence-tail always holds.
        "fence_overlap_saved_ms": round(
            runner.fence_overlap_saved_total_ms, 1),
        # Same-runner strict-order re-measurement: the identical epoch
        # schedule re-run back-to-back on the SAME warm runner with
        # overlap_fence=False, so the only variable is the fence mode
        # (a separately built runner drifts ~10% from ordering/warm
        # state alone on a 1-core host).
        "steady_state_records_per_sec_sequential_control": round(
            throughput_ctrl, 1),
        # The A-B-A overlap re-measurement adjacent to the control:
        # rerun vs control is the drift-bounded mode comparison; the
        # headline vs control spans ~15 minutes of warm-up drift.
        "steady_state_records_per_sec_overlap_rerun": (
            round(throughput_rerun, 1)
            if throughput_rerun is not None else None),
        "subtasks": job.total_subtasks(),
        "device": str(jax.devices()[0].platform),
        # Latency markers (causal-RNG scheduled, replay-stable): pipeline
        # transit time source->sink in causal-time ms. The marker number
        # is CLOSED-LOOP: epochs are pushed back-to-back, so a fence that
        # ran long delays every later record's send without the marker
        # ever seeing it (coordinated omission). "corrected" re-charges
        # each sample the queueing delay of its epoch's fence against a
        # fixed-rate schedule anchored at the first measured fence —
        # the open-loop view (`bench.py --soak` measures it directly).
        "latency_markers": {
            "count": runner.latency.hist.count,
            "p50_ms": runner.latency.hist.quantile(0.5),
            "p99_ms": runner.latency.hist.quantile(0.99),
            "corrected": _soak_slo.corrected_closed_loop(
                runner.latency.samples, fence_walls,
                STEPS_PER_EPOCH, PAR * BATCH),
            "note": "p50/p99 = in-pipeline dwell (closed-loop); "
                    "corrected = dwell + fence queueing delay vs a "
                    "fixed-rate schedule (open-loop equivalent)",
        },
    }
    # Free the headline runner's device state BEFORE the secondary
    # configs build theirs — two multi-GB carries do not coexist on one
    # chip (jax frees buffers on GC).
    import gc
    del runner, report, mgr, replayer, result, warm_runs, warm_report
    # _val retains the executor (and its carry) — dropping `runner`
    # alone would keep the device state alive through the secondary
    # configs below.
    del _val, entries_overlap, entries_seq
    gc.collect()
    # Fence bit-identity at the full 32-subtask shape: two short
    # AUDITED runs of the same job/seed/schedule — pipelined vs strict
    # sequential — then diff their durable digest ledgers. [] proves
    # the overlap changed WHEN the seal/ledger/checkpoint tail ran,
    # never WHAT it recorded.
    if time.monotonic() - T_START > budget_s:
        out["fence_ledger_diff_vs_sequential_control"] = None
    else:
        try:
            import tempfile
            from clonos_tpu.obs.digest import diff_ledgers

            def _audited_ledger(overlap):
                with tempfile.TemporaryDirectory() as td:
                    r = ClusterRunner(job, steps_per_epoch=256,
                                      log_capacity=4096, max_epochs=8,
                                      inflight_ring_steps=1024,
                                      block_steps=256, seed=7,
                                      logical_time=True, audit=True,
                                      checkpoint_dir=td,
                                      overlap_epoch=overlap)
                    r.run_epoch(complete_checkpoint=True)
                    r.run_epoch(complete_checkpoint=False)
                    r.run_epoch(complete_checkpoint=True)
                    r.drain_fence()
                    entries = r.coordinator.read_ledger()
                del r
                gc.collect()
                return entries

            out["fence_ledger_diff_vs_sequential_control"] = diff_ledgers(
                _audited_ledger(False), _audited_ledger(True))
        except Exception as e:                        # pragma: no cover
            out["fence_ledger_diff_vs_sequential_control"] = \
                {"error": str(e)}
    # Secondary BASELINE configs (#4 cascading, #5 join + external-service
    # calls) and the determinant-sharing-depth trade-off sweep. Guarded by
    # a wall-clock budget so the primary metric always prints.
    for key, fn in (("config4_kafka_window_64task_cascading",
                     bench_config4),
                    ("config5_join_128task_external_services",
                     bench_config5)):
        if time.monotonic() - T_START > soft_budget_s:
            out[key] = {"skipped": "bench wall-clock budget exhausted"}
            continue
        try:
            out[key] = fn()
        except Exception as e:                        # pragma: no cover
            out[key] = {"error": str(e)}
        gc.collect()
    try:
        out["sharing_depth_sweep"] = sharing_depth_sweep()
    except Exception as e:                            # pragma: no cover
        out["sharing_depth_sweep"] = {"error": str(e)}
    # FT-overhead attribution probe (profiled, serialized dispatch —
    # never shares the pipelined headline run). Hoists the headline
    # fraction to the top level for dashboards. Runs inside its own
    # reserved slice (see soft_budget_s above) — only a headline run
    # that itself blew through the FULL budget skips it.
    if time.monotonic() - T_START > budget_s:
        out["overhead_probe"] = {"skipped": "bench wall-clock budget "
                                            "exhausted"}
        out["overhead_ft_fraction"] = None
    else:
        try:
            out["overhead_probe"] = overhead_probe()
            out["overhead_ft_fraction"] = \
                out["overhead_probe"]["overhead_ft_fraction"]
        except Exception as e:                        # pragma: no cover
            out["overhead_probe"] = {"error": str(e)}
            out["overhead_ft_fraction"] = None
    # The FT call-site population these numbers were measured against
    # (analysis/census.py): ties the artifact to the exact source shape.
    try:
        from clonos_tpu.analysis import census_fingerprint
        out["census_fingerprint"] = census_fingerprint()
    except Exception:                                 # pragma: no cover
        out["census_fingerprint"] = None
    print(json.dumps(out))


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--jobs", type=int, default=None,
                    help="run the multi-job throughput probe with N "
                         "concurrent jobs instead of the headline bench")
    ap.add_argument("--multichip", type=int, nargs="?", const=8,
                    default=None, metavar="N",
                    help="run the mesh-sharding probe over N devices "
                         "(forcing N host devices when needed) instead "
                         "of the headline bench")
    ap.add_argument("--soak", type=float, nargs="?", const=30.0,
                    default=None, metavar="SECONDS",
                    help="run the open-loop soak probe (fixed-rate "
                         "load + seeded chaos + exactly-once audit) "
                         "instead of the headline bench")
    ap.add_argument("--overhead", action="store_true",
                    help="run ONLY the FT-overhead attribution probe "
                         "(profiled section breakdown + lineage "
                         "on/off cost) instead of the headline bench")
    ap.add_argument("--ablate", action="store_true",
                    help="run the no-FT ablation probe (twin executor "
                         "head-to-head, measured vs static ft-fraction) "
                         "instead of the headline bench")
    ap.add_argument("--spill", action="store_true",
                    help="run the tiered-storage probe (steady-state "
                         "throughput spill on vs off + deep-backlog "
                         "disk-tier recovery, audit-verified) instead "
                         "of the headline bench")
    ap.add_argument("--serve", type=float, nargs="?", const=20.0,
                    default=None, metavar="SECONDS",
                    help="run the read-path probe (batched replica "
                         "reads vs sequential point queries, "
                         "bit-identity vs the owner, mixed read/ingest "
                         "load with a replica-kill) instead of the "
                         "headline bench; writes SERVE_r0N.json")
    ap.add_argument("--rescale", type=float, nargs="?", const=12.0,
                    default=None, metavar="SECONDS",
                    help="run the elastic-repartition probe (live 2->4 "
                         "re-cut at a checkpoint fence under load: "
                         "throughput before/after, fence-stall cost, "
                         "cross-layout ledger diff vs a never-rescaled "
                         "control) instead of the headline bench; "
                         "writes RESCALE_r0N.json")
    _a = ap.parse_args()
    sys.exit(main(jobs=_a.jobs, multichip=_a.multichip, soak=_a.soak,
                  ablate=_a.ablate, spill=_a.spill, serve=_a.serve,
                  rescale=_a.rescale, overhead=_a.overhead))
