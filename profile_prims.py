"""Microbenchmark TPU primitive costs, all inside lax.scan (real usage shape)."""
import time
import jax, jax.numpy as jnp
import numpy as np

def bench_scan(label, body, carry0, steps=64, n=3):
    @jax.jit
    def run(c):
        return jax.lax.scan(lambda c, _: (body(c), ()), c, None, length=steps)[0]
    r = jax.block_until_ready(run(carry0))
    t0 = time.monotonic()
    for _ in range(n):
        r = run(r)
    jax.block_until_ready(r)
    dt = (time.monotonic() - t0) / n / steps
    print(f"{label}: {dt*1e6:.1f} us/step")
    return dt

N = 8192
T = 8
CAP = 1024
K = 997
key = jax.random.PRNGKey(0)
tgt0 = jax.random.randint(key, (N,), 0, T, jnp.int32)
keys1k = jax.random.randint(key, (CAP,), 0, K, jnp.int32)

# perturb carry so XLA can't hoist
def mix(c):
    return (c * 1103515245 + 12345) & 0x7FFFFFFF

# A. argsort in scan
bench_scan("argsort 8192", lambda c: mix(c) + jnp.argsort((tgt0 + c) % T, stable=True)[0],
           jnp.zeros((), jnp.int32))

# B. cumsum+unique scatter route
def route_cs(c):
    tgt = (tgt0 + c) % T
    oh = (tgt[:, None] == jnp.arange(T)[None, :]).astype(jnp.int32)
    pos = jnp.cumsum(oh, axis=0)
    p = pos[jnp.arange(N), tgt] - 1
    keep = p < CAP
    row = jnp.where(keep, tgt, T)
    col = jnp.where(keep, p, 0)
    out = jnp.zeros((T + 1, CAP), jnp.int32).at[row, col].set(
        tgt, mode="drop", unique_indices=True)
    return mix(c) + out[0, 0]
bench_scan("route cumsum+unique-scatter 8192", route_cs, jnp.zeros((), jnp.int32))

# C. scatter-add 1024->997 vs one-hot matmul
bench_scan("scatter-add 1024->997",
           lambda acc: acc.at[keys1k].add(1, mode="drop"),
           jnp.zeros((K,), jnp.int32), steps=128)

ohc = (keys1k[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
bench_scan("onehot-matvec 1024x997 (precomp oh)",
           lambda acc: acc + ohc.T @ jnp.ones((CAP,), jnp.float32),
           jnp.zeros((K,), jnp.float32), steps=128)

def mm_dyn(acc):
    keys = (keys1k + acc[0].astype(jnp.int32)) % K
    oh = (keys[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
    return acc + oh.T @ jnp.ones((CAP,), jnp.float32)
bench_scan("onehot-matvec dynamic oh", mm_dyn, jnp.zeros((K,), jnp.float32), steps=128)

# C2. batched version: [8, 1024] -> [8, 997] (vmap over subtasks)
keys2 = jax.random.randint(key, (T, CAP), 0, K, jnp.int32)
def mm_batched(acc):
    keys = (keys2 + acc[0, 0].astype(jnp.int32)) % K
    oh = (keys[..., None] == jnp.arange(K)[None, None, :]).astype(jnp.float32)
    contrib = jnp.einsum("pbk,pb->pk", oh, jnp.ones((T, CAP), jnp.float32),
                         preferred_element_type=jnp.float32)
    return acc + contrib
bench_scan("batched onehot 8x1024x997", mm_batched, jnp.zeros((T, K), jnp.float32), steps=64)

def sc_batched(acc):
    keys = (keys2 + acc[0, 0]) % K
    return jax.vmap(lambda a, k: a.at[k].add(1, mode="drop"))(acc, keys)
bench_scan("batched scatter-add 8x1024->8x997", sc_batched, jnp.zeros((T, K), jnp.int32), steps=64)

# D. small DUS into big ring, in scan (in-flight append analog)
ring0 = jnp.zeros((512, T, CAP), jnp.int32)
def dus_ring(ring):
    i = ring[0, 0, 0] % 512
    blk = jnp.full((1, T, CAP), ring[0, 0, 1] + 1, jnp.int32)
    return jax.lax.dynamic_update_slice(ring, blk, (i, 0, 0))
bench_scan("DUS [1,8,1024] into [512,8,1024]", dus_ring, ring0, steps=128)

# E. det append: [32,4,8] scatter into [32,2048,8] at head (per-step path)
logs0 = (jnp.zeros((32, 2048, 8), jnp.int32), jnp.zeros((), jnp.int32))
def det_append(s):
    rows, head = s
    blk = jnp.full((32, 4, 8), head, jnp.int32)
    idx = (head + jnp.arange(4)) & 2047
    rows = rows.at[:, idx].set(blk)
    return (rows, head + 4)
bench_scan("det append [32,4,8] into [32,2048,8]", det_append, logs0, steps=128)

# F. replica direct append: gather [384 owners] + scatter
own_idx = jnp.asarray(np.random.randint(0, 32, 384), jnp.int32)
reps0 = (jnp.zeros((384, 2048, 8), jnp.int32), jnp.zeros((), jnp.int32))
def rep_append(s):
    rows, head = s
    blk = jnp.full((32, 4, 8), head, jnp.int32)
    rblk = blk[own_idx]
    idx = (head + jnp.arange(4)) & 2047
    rows = rows.at[:, idx].set(rblk)
    return (rows, head + 4)
bench_scan("replica append [384,4,8]", rep_append, reps0, steps=128)
