import sys, time
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from clonos_tpu.api.records import RecordBatch, zero_invalid
from clonos_tpu.parallel import routing
from clonos_tpu.utils.devsync import device_sync as sync

K, P, B, T, CAP = 512, 8, 128, 8, 1024
rng = np.random.RandomState(0)
batch = RecordBatch(jnp.asarray(rng.randint(0, 997, (K, P, B)), jnp.int32),
                    jnp.asarray(rng.randint(0, 99, (K, P, B)), jnp.int32),
                    jnp.asarray(rng.randint(0, 9, (K, P, B)), jnp.int32),
                    jnp.asarray(rng.rand(K, P, B) < 0.9))

def count_route(batch, target, T, cap):
    K, P, B = batch.keys.shape
    n = P * B
    fl = lambda x: x.reshape(K, n)
    keys, vals, ts, valid = map(fl, batch)
    tgt = jnp.where(valid, fl(target), T)
    onehot = (tgt[:, :, None] ==
              jnp.arange(T + 1, dtype=jnp.int32)[None, None, :])
    pos_all = jnp.cumsum(onehot.astype(jnp.int32), axis=1)
    pos = jnp.take_along_axis(pos_all, tgt[:, :, None], axis=2)[:, :, 0] - 1
    counts = pos_all[:, -1, :T]
    live = tgt < T
    keep = live & (pos < cap)
    dropped = jnp.maximum(counts - cap, 0).astype(jnp.int32)
    row = jnp.where(keep, tgt, T)
    col = jnp.where(keep, pos, 0)
    kidx = jnp.arange(K, dtype=jnp.int32)[:, None]
    shape = (K, T + 1, cap)
    mk = lambda src, z: jnp.zeros(shape, z).at[kidx, row, col].set(
        src, mode="drop")
    out = RecordBatch(mk(keys, jnp.int32), mk(vals, jnp.int32),
                      mk(ts, jnp.int32), mk(keep, jnp.bool_))
    out = RecordBatch(out.keys[:, :T], out.values[:, :T],
                      out.timestamps[:, :T], out.valid[:, :T])
    return zero_invalid(out), dropped

def hash_count(b, cap):
    kg = routing.key_group(b.keys, 64)
    t = routing.subtask_for_key_group(kg, T, 64)
    return count_route(b, t, T, cap)

# bit-identity vs the existing exchange
ref, dref = jax.jit(lambda b: routing.route_hash_block(b, T, 64, CAP))(batch)
new, dnew = jax.jit(lambda b: hash_count(b, CAP))(batch)
for a, bb in zip(ref, new):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))
np.testing.assert_array_equal(np.asarray(dref), np.asarray(dnew))
print("bit-identical incl. drops", flush=True)

def timeit(name, fn, *args, n=10):
    jfn = jax.jit(fn)
    out = jfn(*args); sync(out)
    t0 = time.monotonic()
    for _ in range(n):
        out = jfn(*args)
    sync(out)
    print(f"{name:40s} {(time.monotonic()-t0)/n*1e3:8.2f} ms", flush=True)

for cap in (1024, 256):
    timeit(f"sort exchange cap={cap}",
           lambda b, c=cap: routing.route_hash_block(b, T, 64, c), batch)
    timeit(f"count exchange cap={cap}",
           lambda b, c=cap: hash_count(b, c), batch)
# skew: all records one target
skew = batch._replace(keys=jnp.zeros((K, P, B), jnp.int32))
r1 = jax.jit(lambda b: routing.route_hash_block(b, T, 64, 256))(skew)
r2 = jax.jit(lambda b: hash_count(b, 256))(skew)
for a, bb in zip(r1[0], r2[0]):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))
np.testing.assert_array_equal(np.asarray(r1[1]), np.asarray(r2[1]))
print("skew/overflow bit-identical", flush=True)
