#!/usr/bin/env python
"""Shim: the dissection moved into the package CLI as ``clonos_tpu
dissect`` (clonos_tpu/cli.py:cmd_dissect) so it shares the subcommand
plumbing instead of carrying its own bootstrap. This wrapper keeps the
old ``python tools/replay_dissect.py`` invocation working."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from clonos_tpu.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["dissect"] + sys.argv[1:]))
