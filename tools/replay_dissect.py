#!/usr/bin/env python
"""Dissect the warm replay at full bench shapes: what the min-of-5
``replayer.replay(plan)`` wall actually spends — dispatch-chain compute
(amortized over a chained loop, tunnel RTT excluded) vs the single d2h
sync. Optimization must target whichever dominates."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import bench
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP
    from clonos_tpu.utils.devsync import device_sync

    SPE = bench.STEPS_PER_EPOCH
    job = bench.build_job()
    need = bench.FILL_EPOCHS * SPE * DETS_PER_STEP
    cap = 1 << need.bit_length()
    runner = ClusterRunner(job, steps_per_epoch=SPE, log_capacity=cap,
                           max_epochs=16,
                           inflight_ring_steps=1 << max(
                               bench.FILL_EPOCHS * SPE, 2).bit_length(),
                           recovery_block_steps=8192, block_steps=1024,
                           seed=7)
    t0 = time.monotonic()
    runner.run_epoch(complete_checkpoint=True)
    device_sync(runner.executor.carry)
    print("epoch0:", round(time.monotonic() - t0, 1), "s", flush=True)
    t0 = time.monotonic()
    for _ in range(bench.FILL_EPOCHS):
        runner.run_epoch(complete_checkpoint=False)
    device_sync(runner.executor.carry)
    print("fill:", round(time.monotonic() - t0, 1), "s", flush=True)

    failed = bench.PAR + 1
    runner.inject_failure([failed])
    t0 = time.monotonic()
    report = runner.recover()
    device_sync(runner.executor.carry)
    print("cold recover:", round(time.monotonic() - t0, 1), "s",
          {k: round(v, 1) for k, v in report.phase_ms.items()}, flush=True)

    mgr = report.managers[0]
    replayer = mgr.replayer
    plan = mgr.plan

    # (a) bench's exact warm-replay measurement
    for trial in range(5):
        t1 = time.monotonic()
        result = replayer.replay(plan)
        device_sync(result.emit_counts)
        print(f"warm replay #{trial}: "
              f"{(time.monotonic() - t1) * 1e3:.1f}ms  phases:",
              {k: round(v, 1) for k, v in result.phase_ms.items()},
              flush=True)

    # (b) amortized compute of the core block program alone (tunnel RTT
    # excluded): chain N iterations inside one jit, one sync at the end.
    dev = plan.det_device is not None
    print("clean device path:", dev, "n_steps:", plan.n_steps, flush=True)
    if dev:
        t_dev, r_dev, _exp = plan.det_device
        chunk = plan.input_steps[0] if isinstance(plan.input_steps, list) \
            else plan.input_steps
        state0 = jax.tree_util.tree_map(
            lambda x: x[plan.subtask][None], plan.checkpoint_op_state)
        sub = jnp.asarray(plan.subtask, jnp.int32)
        N = 10
        jb = replayer._jit_block

        def chained():
            acc = jnp.zeros((), jnp.int32)
            for _ in range(N):
                st, out, counts, acc = jb(
                    state0, chunk, t_dev[:replayer.block_steps],
                    r_dev[:replayer.block_steps], sub, acc)
            return counts
        r = chained()
        np.asarray(r.ravel()[0])
        ts = []
        for _ in range(3):
            t1 = time.monotonic()
            r = chained()
            np.asarray(r.ravel()[0])
            ts.append((time.monotonic() - t1) * 1e3)
        print(f"block program amortized: {min(ts) / N:.2f}ms per call "
              f"(chain of {N}: {min(ts):.1f}ms)", flush=True)

        # (c) tail ops: tslice + concat cost
        def tail():
            acc = jnp.zeros((), jnp.int32)
            st, out, counts, acc = jb(state0, chunk,
                                      t_dev[:replayer.block_steps],
                                      r_dev[:replayer.block_steps], sub, acc)
            packed = jnp.concatenate(
                [counts, acc.reshape(1), _exp[:plan.n_steps]], axis=0)
            return packed
        p = tail()
        np.asarray(p.ravel()[0])
        ts = []
        for _ in range(5):
            t1 = time.monotonic()
            p = tail()
            np.asarray(p.ravel()[0])
            ts.append((time.monotonic() - t1) * 1e3)
        print(f"block+concat+sync single: min={min(ts):.1f}ms "
              f"p50={sorted(ts)[2]:.1f}ms", flush=True)


if __name__ == "__main__":
    main()
