#!/usr/bin/env python
"""Round 2 of kernel A/B: prefix-sum formulations (the block programs'
dominant cost) and block-flat exchange."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.records import RecordBatch, zero_invalid
from clonos_tpu.parallel import routing


def timeit(fn, *args, n=10):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), out


# --- prefix-sum formulations ----------------------------------------------

def cumsum_native(x):
    return jnp.cumsum(x, axis=0)


def cumsum_ascan(x):
    return jax.lax.associative_scan(jnp.add, x, axis=0)


def _tri(n, dtype):
    i = jnp.arange(n)
    return (i[:, None] >= i[None, :]).astype(dtype)


def cumsum_matmul_f32(x):
    # Exact while |prefix| < 2^24; x int32 [K, ...]
    K = x.shape[0]
    tri = _tri(K, jnp.float32)
    flat = x.reshape(K, -1).astype(jnp.float32)
    return jnp.dot(tri, flat, preferred_element_type=jnp.float32
                   ).astype(jnp.int32).reshape(x.shape)


def cumsum_matmul_exact(x):
    # Exact for full int32: split into 16-bit halves (unsigned lo).
    K = x.shape[0]
    tri = _tri(K, jnp.float32)
    flat = x.reshape(K, -1)
    lo = (flat & 0xFFFF).astype(jnp.float32)
    hi = (flat >> 16).astype(jnp.float32)
    slo = jnp.dot(tri, lo, preferred_element_type=jnp.float32)
    shi = jnp.dot(tri, hi, preferred_element_type=jnp.float32)
    # prefixes of 16-bit halves stay < 2^24 for K < 256... not generally.
    # For exactness across K up to 512: lo sums < 512*65535 < 2^25 — NOT
    # exactly representable past 2^24. Use two-level: chunk 128.
    out = (slo.astype(jnp.int64) + (shi.astype(jnp.int64) << 16)
           ).astype(jnp.int32)
    return out.reshape(x.shape)


def cumsum_chunked(x, chunk=128):
    # Two-level: in-chunk tri-matmul (f32 exact: chunk*2^16 < 2^24), plus
    # exclusive carry of chunk totals.
    K = x.shape[0]
    assert K % chunk == 0
    C = K // chunk
    tri = _tri(chunk, jnp.float32)
    flat = x.reshape(C, chunk, -1)
    lo = (flat & 0xFFFF).astype(jnp.float32)
    hi = (flat >> 16).astype(jnp.float32)
    slo = jnp.einsum("ij,cjn->cin", tri, lo,
                     preferred_element_type=jnp.float32).astype(jnp.int64)
    shi = jnp.einsum("ij,cjn->cin", tri, hi,
                     preferred_element_type=jnp.float32).astype(jnp.int64)
    within = (slo + (shi << 16)).astype(jnp.int32)        # [C, chunk, n]
    totals = within[:, -1]                                 # [C, n]
    carry = jnp.cumsum(totals, axis=0) - totals            # exclusive [C, n]
    return (within + carry[:, None]).reshape(x.shape)


def main():
    print("device:", jax.devices()[0].platform)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, 1000, size=(512, 8, 997)), jnp.int32)

    ref = None
    for name, fn in [("native", cumsum_native), ("ascan", cumsum_ascan),
                     ("matmul_f32", cumsum_matmul_f32),
                     ("chunked", cumsum_chunked)]:
        t, out = timeit(jax.jit(fn), x)
        if ref is None:
            ref = out
        eq = bool(jnp.array_equal(ref, out))
        print(f"cumsum [512,8,997] {name}: {t*1e3:.2f}ms exact={eq}")

    # big-int exactness check for chunked
    xb = jnp.asarray(rng.randint(-2**28, 2**28, size=(512, 64)), jnp.int32)
    eq = bool(jnp.array_equal(jnp.cumsum(xb, axis=0),
                              jax.jit(cumsum_chunked)(xb)))
    print("chunked exact on +-2^28 values:", eq)

    # [n, T] position cumsum shape (exchange): [512, 7976, 8] along axis 1
    oh = jnp.asarray(rng.randint(0, 2, size=(512, 7976, 8)), jnp.int32)
    t, _ = timeit(jax.jit(lambda v: jnp.cumsum(v, axis=1)), oh)
    print(f"pos-cumsum [512,7976,8] native: {t*1e3:.2f}ms")
    def chunk_ax1(v):
        K, n, T = v.shape
        pad = (-n) % 128
        vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
        out = cumsum_chunked(vp.transpose(1, 0, 2).reshape(n + pad, -1))
        return out.reshape(n + pad, K, T).transpose(1, 0, 2)[:, :n]
    t, _ = timeit(jax.jit(chunk_ax1), oh)
    print(f"pos-cumsum [512,7976,8] chunked-matmul: {t*1e3:.2f}ms")

    # --- block-flat sort vs per-step sort ---------------------------------
    K, P, B = 512, 8, 997
    n = P * B
    tgt = jnp.asarray(rng.randint(0, 9, size=(K, n)), jnp.int32)
    def per_step(tv):
        return jax.vmap(lambda t: jnp.argsort(t, stable=True))(tv)
    def flat_sort(tv):
        key = tv + jnp.arange(K, dtype=jnp.int32)[:, None] * 16
        return jnp.argsort(key.reshape(-1), stable=True)
    t1, _ = timeit(jax.jit(per_step), tgt)
    t2, _ = timeit(jax.jit(flat_sort), tgt)
    print(f"argsort per-step [512x{n}]: {t1*1e3:.2f}ms   "
          f"flat [{K*n}]: {t2*1e3:.2f}ms")

    # contrib at smaller capacity
    for cap in (128, 256, 1024):
        keys = jnp.asarray(rng.randint(0, 997, size=(K, P, cap)), jnp.int32)
        vals = jnp.ones((K, P, cap), jnp.int32)
        valid = jnp.asarray(rng.rand(K, P, cap) < 0.5)
        def contrib(k, v, m):
            step = jnp.broadcast_to(
                jnp.arange(K, dtype=jnp.int32)[:, None, None], k.shape)
            sub = jnp.broadcast_to(
                jnp.arange(P, dtype=jnp.int32)[None, :, None], k.shape)
            return jnp.zeros((K, P, 997), jnp.int32).at[step, sub, k].add(
                jnp.where(m, v, 0), mode="drop")
        t, _ = timeit(jax.jit(contrib), keys, vals, valid)
        print(f"contrib scatter cap={cap}: {t*1e3:.2f}ms")


if __name__ == "__main__":
    main()
