#!/usr/bin/env python
"""A/B micro-bench of exchange + keyed-aggregation formulations on the
real chip. Findings land directly in parallel/routing.py and
api/operators.py (round-3 verdict: profile output must turn into landed
optimizations)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.records import RecordBatch, zero_invalid
from clonos_tpu.parallel import routing


def timeit(fn, *args, n=10):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def make_batch(K, P, B, vocab, seed=0):
    rng = np.random.RandomState(seed)
    keys = rng.randint(0, vocab, size=(K, P, B)).astype(np.int32)
    vals = np.ones((K, P, B), np.int32)
    ts = rng.randint(0, 1000, size=(K, P, B)).astype(np.int32)
    valid = rng.rand(K, P, B) < 0.8
    return zero_invalid(RecordBatch(jnp.asarray(keys), jnp.asarray(vals),
                                    jnp.asarray(ts), jnp.asarray(valid)))


# --- formulation B: position via one-hot cumsum, gather output ------------

def route_hash_gather(batch, parallelism, num_key_groups, out_capacity):
    """Sort-free exchange: target via hash, per-target positions via
    cumsum of one-hot [n, T]; output built by GATHER from a scatter of
    record indices (unique destinations)."""
    kg = routing.key_group(batch.keys, num_key_groups)
    target = routing.subtask_for_key_group(kg, parallelism, num_key_groups)
    n = batch.keys.size
    T = parallelism
    flat = lambda x: jnp.reshape(x, (n,))
    keys, vals, ts, valid = map(flat, batch)
    tgt = jnp.where(valid, flat(target), T)
    onehot = (tgt[:, None] == jnp.arange(T, dtype=jnp.int32)[None, :])
    pos_all = jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1   # [n, T]
    pos = jnp.take_along_axis(pos_all, jnp.clip(tgt, 0, T - 1)[:, None],
                              axis=1)[:, 0]
    keep = (tgt < T) & (pos < out_capacity)
    counts = pos_all[-1] + 1                                      # [T]
    dropped = jnp.maximum(counts - out_capacity, 0).astype(jnp.int32)
    # Scatter record indices into the [T, cap] layout (unique dests),
    # then gather payload lanes.
    dest = jnp.where(keep, tgt * out_capacity + pos, T * out_capacity)
    idx = jnp.zeros((T * out_capacity + 1,), jnp.int32).at[dest].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop",
        unique_indices=True)
    got = jnp.zeros((T * out_capacity + 1,), jnp.bool_).at[dest].set(
        keep, mode="drop", unique_indices=True)
    idx = idx[:T * out_capacity].reshape(T, out_capacity)
    got = got[:T * out_capacity].reshape(T, out_capacity)
    out = RecordBatch(keys[idx], vals[idx], ts[idx], got)
    return zero_invalid(out), dropped


# --- formulation C: argsort kept, scatter replaced by gather --------------

def route_hash_sort_gather(batch, parallelism, num_key_groups, out_capacity):
    kg = routing.key_group(batch.keys, num_key_groups)
    target = routing.subtask_for_key_group(kg, parallelism, num_key_groups)
    n = batch.keys.size
    T = parallelism
    flat = lambda x: jnp.reshape(x, (n,))
    keys, vals, ts, valid = map(flat, batch)
    tgt = jnp.where(valid, flat(target), T)
    order = jnp.argsort(tgt, stable=True)
    st = tgt[order]
    run_start = jnp.searchsorted(
        st, jnp.arange(T + 1, dtype=st.dtype), side="left").astype(jnp.int32)
    run_len = jnp.diff(jnp.concatenate(
        [run_start, jnp.asarray([n], jnp.int32)]))[:T]
    dropped = jnp.maximum(run_len - out_capacity, 0).astype(jnp.int32)
    c = jnp.arange(out_capacity, dtype=jnp.int32)
    src = run_start[:T, None] + c[None, :]                        # [T, cap]
    ok = c[None, :] < jnp.minimum(run_len, out_capacity)[:, None]
    src = jnp.clip(src, 0, n - 1)
    pick = order[src]
    out = RecordBatch(keys[pick], vals[pick], ts[pick], ok)
    return zero_invalid(out), dropped


# --- aggregation formulations ---------------------------------------------

def contrib_scatter(keys, values, valid, nk):
    K, p, _ = keys.shape
    step = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None, None],
                            keys.shape)
    sub = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32)[None, :, None],
                           keys.shape)
    return jnp.zeros((K, p, nk), jnp.int32).at[step, sub, keys].add(
        jnp.where(valid, values, 0), mode="drop")


def contrib_matmul(keys, values, valid, nk):
    # One-hot matmul: exact for |values| < 2^24 summed counts (fp32 accum).
    K, p, B = keys.shape
    kf = keys.reshape(K * p, B)
    vf = jnp.where(valid, values, 0).reshape(K * p, B).astype(jnp.float32)
    oh = jax.nn.one_hot(kf, nk, dtype=jnp.float32)            # [KP, B, nk]
    out = jnp.einsum("xb,xbn->xn", vf, oh,
                     preferred_element_type=jnp.float32)
    return out.reshape(K, p, nk).astype(jnp.int32)


def main():
    print("device:", jax.devices()[0].platform)
    K, P, B = 512, 8, 128
    CAP = 1024
    NKG = 64
    batch = make_batch(K, P, B, vocab=997)

    # Exchange over the source->window edge shape ([K,P,B] flat per step).
    cur = jax.jit(jax.vmap(lambda b: routing.route_hash(b, P, NKG, CAP)))
    gat = jax.jit(jax.vmap(lambda b: route_hash_gather(b, P, NKG, CAP)))
    sg = jax.jit(jax.vmap(lambda b: route_hash_sort_gather(b, P, NKG, CAP)))
    t_cur, r_cur = timeit(cur, batch)
    t_gat, r_gat = timeit(gat, batch)
    t_sg, r_sg = timeit(sg, batch)
    print(f"exchange n={P*B}: current(sort+scatter) {t_cur*1e3:.2f}ms  "
          f"cumsum+gather {t_gat*1e3:.2f}ms  sort+gather {t_sg*1e3:.2f}ms")
    for name, r in [("cumsum+gather", r_gat), ("sort+gather", r_sg)]:
        same = all(bool(jnp.array_equal(a, b))
                   for a, b in zip(jax.tree_util.tree_leaves(r_cur),
                                   jax.tree_util.tree_leaves(r)))
        print(f"  bit-identical vs current: {name}: {same}")

    # Exchange over the window->reduce edge shape (n = P*997).
    big = make_batch(K, P, 997, vocab=997, seed=1)
    cur2 = jax.jit(jax.vmap(lambda b: routing.route_hash(b, P, NKG, CAP)))
    gat2 = jax.jit(jax.vmap(lambda b: route_hash_gather(b, P, NKG, CAP)))
    sg2 = jax.jit(jax.vmap(lambda b: route_hash_sort_gather(b, P, NKG, CAP)))
    t_cur2, r_cur2 = timeit(cur2, big)
    t_gat2, r_gat2 = timeit(gat2, big)
    t_sg2, r_sg2 = timeit(sg2, big)
    print(f"exchange n={P*997}: current {t_cur2*1e3:.2f}ms  "
          f"cumsum+gather {t_gat2*1e3:.2f}ms  sort+gather {t_sg2*1e3:.2f}ms")
    same2 = all(bool(jnp.array_equal(a, b))
                for a, b in zip(jax.tree_util.tree_leaves(r_cur2),
                                jax.tree_util.tree_leaves(r_gat2)))
    print(f"  bit-identical cumsum+gather: {same2}")

    # Aggregation contrib at the window shape.
    nk = 997
    inb = make_batch(K, P, CAP, vocab=nk, seed=2)
    sc = jax.jit(lambda b: contrib_scatter(b.keys, b.values, b.valid, nk))
    mm = jax.jit(lambda b: contrib_matmul(b.keys, b.values, b.valid, nk))
    t_sc, r_sc = timeit(sc, inb)
    t_mm, r_mm = timeit(mm, inb)
    print(f"contrib [K={K},P={P},B={CAP}]->nk={nk}: scatter {t_sc*1e3:.2f}ms"
          f"  matmul {t_mm*1e3:.2f}ms  equal:"
          f" {bool(jnp.array_equal(r_sc, r_mm))}")

    # cumsum over steps (the prefix the window/reduce blocks need).
    csum = jax.jit(lambda x: jnp.cumsum(x, axis=0))
    t_cs, _ = timeit(csum, r_sc)
    print(f"cumsum [K,P,nk]: {t_cs*1e3:.2f}ms")


if __name__ == "__main__":
    main()
