#!/usr/bin/env python
"""Cold vs warm recovery phase breakdown at bench shapes — what still
compiles or stalls inside the first post-prewarm recover()."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    os.environ.setdefault("BENCH_STEPS_PER_EPOCH", "4096")
    import bench
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP
    from clonos_tpu.utils.devsync import device_sync

    SPE = int(os.environ["BENCH_STEPS_PER_EPOCH"])
    job = bench.build_job()
    need = bench.FILL_EPOCHS * SPE * DETS_PER_STEP
    cap = 1 << need.bit_length()
    runner = ClusterRunner(job, steps_per_epoch=SPE, log_capacity=cap,
                           max_epochs=16,
                           inflight_ring_steps=1 << max(
                               bench.FILL_EPOCHS * SPE, 2).bit_length(),
                           recovery_block_steps=2048, seed=7)
    t0 = time.monotonic()
    runner.run_epoch(complete_checkpoint=True)
    device_sync(runner.executor.carry)
    print("epoch0:", round(time.monotonic() - t0, 1), "s", flush=True)
    t0 = time.monotonic()
    pw = runner.prewarm_recovery()
    print("prewarm:", round(pw, 1), "s", flush=True)
    for _ in range(bench.FILL_EPOCHS):
        runner.run_epoch(complete_checkpoint=False)
    device_sync(runner.executor.carry)
    for label in ("cold", "warm1", "warm2"):
        runner.inject_failure([9])
        t0 = time.monotonic()
        report = runner.recover()
        device_sync(runner.executor.carry)
        total = time.monotonic() - t0
        print(label, round(total * 1e3, 1), "ms phases:",
              json.dumps({k: round(v, 1)
                          for k, v in report.phase_ms.items()}),
              flush=True)
        print("   replay phases:", json.dumps(
            {k: round(v, 1) for k, v in
             report.managers[0].result.phase_ms.items()}), flush=True)


if __name__ == "__main__":
    main()
