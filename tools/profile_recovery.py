#!/usr/bin/env python
"""Recovery-path profiler: phase breakdown of cold and warm recovery at
(scaled-down) bench topology. Drives the same workload as bench.py and
prints per-phase wall-clock so optimization targets the real bottleneck.

Env knobs: PROF_STEPS_PER_EPOCH (default 1024), PROF_PAR (default 8),
PROF_BATCH (default 128), PROF_FAIL (flat subtask, default window s1).
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP
    from clonos_tpu.api.environment import StreamEnvironment

    spe = int(os.environ.get("PROF_STEPS_PER_EPOCH", 1024))
    par = int(os.environ.get("PROF_PAR", 8))
    batch = int(os.environ.get("PROF_BATCH", 128))
    fill = 2

    env = StreamEnvironment(name="prof", num_key_groups=64,
                            default_edge_capacity=1024)
    (env.synthetic_source(vocab=997, batch_size=batch, parallelism=par)
        .key_by()
        .window_count(num_keys=997, window_size=1 << 30, name="window")
        .key_by()
        .reduce(num_keys=997, name="reduce")
        .sink())
    job = env.build()

    need = (fill + 1) * spe * DETS_PER_STEP
    cap = 1 << max(need - 1, 1).bit_length()
    runner = ClusterRunner(
        job, steps_per_epoch=spe, log_capacity=cap, max_epochs=16,
        inflight_ring_steps=1 << max(fill * spe, 2).bit_length(), seed=7)

    t0 = time.monotonic()
    runner.run_epoch(complete_checkpoint=True)
    jax.block_until_ready(runner.executor.carry)
    t_epoch0 = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(fill):
        runner.run_epoch(complete_checkpoint=False)
    jax.block_until_ready(runner.executor.carry)
    t_fill = time.monotonic() - t0

    failed = int(os.environ.get("PROF_FAIL", par + 1))
    runner.inject_failure([failed])
    t0 = time.monotonic()
    report = runner.recover()
    jax.block_until_ready(runner.executor.carry)
    cold_s = time.monotonic() - t0

    mgr = report.managers[0]
    t0 = time.monotonic()
    result = mgr.replayer.replay(mgr.plan)
    jax.block_until_ready(result.emit_counts)
    warm_s = time.monotonic() - t0

    out = {
        "steps_per_epoch": spe, "par": par, "batch": batch,
        "epoch0_s": round(t_epoch0, 2), "fill_s": round(t_fill, 2),
        "steady_records_per_sec": round(
            fill * spe * par * batch / t_fill, 0),
        "cold_recovery_s": round(cold_s, 2),
        "cold_phases_ms": {k: round(v, 1)
                           for k, v in report.phase_ms.items()},
        "warm_replay_s": round(warm_s, 3),
        "warm_phases_ms": {k: round(v, 1)
                           for k, v in result.phase_ms.items()},
        "records_replayed": report.records_replayed,
        "warm_records_per_sec": round(report.records_replayed / warm_s, 0),
        "device": str(jax.devices()[0].platform),
    }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    sys.exit(main())
