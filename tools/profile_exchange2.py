#!/usr/bin/env python
"""Round 2: does TPU XLA exploit indices_are_sorted / unique_indices?

Candidates for the compaction step after the co-sort:
  (a) gather with monotone clipped src + indices_are_sorted=True
  (b) flat scatter to dest = tgt*CAP + pos with sorted+unique flags
  (c) block-flat: one co-sort of the whole [K*n] block by (step,tgt) then
      one flat sorted gather
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.records import RecordBatch, zero_invalid
from clonos_tpu.parallel import routing

K, P, B, CAP, NK = 512, 8, 997, 1024, 997


def _sync(tree):
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "shape")]
    x = leaves[0]
    np.asarray(x.ravel()[0] if x.ndim else x)


def timeit(name, fn, *args, n=10):
    jfn = jax.jit(fn)
    out = jfn(*args)
    _sync(out)
    t0 = time.monotonic()
    _sync(out)
    rt = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(n):
        out = jfn(*args)
    _sync(out)
    ms = ((time.monotonic() - t0) - rt) / n * 1e3
    print(f"{name:48s} {ms:9.2f} ms")
    return ms


def _tgt(batch):
    kg = routing.key_group(batch.keys, 64)
    return routing.subtask_for_key_group(kg, P, 64)


def sorted_gather(batch: RecordBatch):
    n = batch.keys.size
    flat = lambda x: x.reshape((n,))
    tgt = jnp.where(flat(batch.valid), flat(_tgt(batch)), P)
    st, sk, sv, sts = jax.lax.sort(
        (tgt, flat(batch.keys), flat(batch.values), flat(batch.timestamps)),
        num_keys=1, is_stable=True)
    run_start = jnp.searchsorted(
        st, jnp.arange(P + 1, dtype=st.dtype), side="left").astype(jnp.int32)
    j = jnp.arange(CAP, dtype=jnp.int32)
    src = run_start[:P, None] + j[None, :]
    ok = src < run_start[1:, None]
    # monotone src: clip each row's overhang to the next run start
    srcm = jnp.minimum(src, run_start[1:, None])
    srcm = jnp.minimum(srcm, n - 1)
    take = functools.partial(jnp.take, indices_are_sorted=True, axis=0)
    out = RecordBatch(take(sk, srcm.ravel()).reshape(P, CAP),
                      take(sv, srcm.ravel()).reshape(P, CAP),
                      take(sts, srcm.ravel()).reshape(P, CAP), ok)
    return zero_invalid(out)


def sorted_scatter(batch: RecordBatch):
    n = batch.keys.size
    flat = lambda x: x.reshape((n,))
    tgt = jnp.where(flat(batch.valid), flat(_tgt(batch)), P)
    st, sk, sv, sts = jax.lax.sort(
        (tgt, flat(batch.keys), flat(batch.values), flat(batch.timestamps)),
        num_keys=1, is_stable=True)
    run_start = jnp.searchsorted(
        st, jnp.arange(P + 1, dtype=st.dtype), side="left").astype(jnp.int32)
    i = jnp.arange(n, dtype=jnp.int32)
    pos = i - run_start[jnp.clip(st, 0, P)]
    keep = (st < P) & (pos < CAP)
    dest = jnp.where(keep, st * CAP + pos, P * CAP)   # monotone non-decreasing
    z = jnp.zeros((P * CAP + 1,), jnp.int32)
    sset = lambda zz, x: zz.at[dest].set(
        x, mode="drop", unique_indices=False, indices_are_sorted=True)
    out = RecordBatch(
        sset(z, sk)[:P * CAP].reshape(P, CAP),
        sset(z, sv)[:P * CAP].reshape(P, CAP),
        sset(z, sts)[:P * CAP].reshape(P, CAP),
        sset(z, keep.astype(jnp.int32))[:P * CAP].reshape(P, CAP) > 0)
    return out


def block_flat_gather(batch: RecordBatch):
    """One sort for the whole block: key = step*(P+1) + tgt."""
    Kn = batch.keys.size
    n = P * B
    flat = lambda x: x.reshape((Kn,))
    tgt = jnp.where(batch.valid, _tgt(batch), P).reshape(K, n)
    step = jnp.arange(K, dtype=jnp.int32)[:, None]
    skey = (step * (P + 1) + tgt).reshape(Kn)
    st, sk, sv, sts = jax.lax.sort(
        (skey, flat(batch.keys), flat(batch.values), flat(batch.timestamps)),
        num_keys=1, is_stable=True)
    bounds = jnp.arange(K * (P + 1) + 1, dtype=st.dtype)
    run_start = jnp.searchsorted(st, bounds, side="left").astype(jnp.int32)
    rs = run_start[: K * (P + 1)].reshape(K, P + 1)
    re_ = run_start[1: K * (P + 1) + 1].reshape(K, P + 1)
    j = jnp.arange(CAP, dtype=jnp.int32)
    src = rs[:, :P, None] + j[None, None, :]
    ok = src < re_[:, :P, None]
    srcm = jnp.minimum(jnp.minimum(src, re_[:, :P, None]), Kn - 1)
    take = functools.partial(jnp.take, indices_are_sorted=True, axis=0)
    out = RecordBatch(take(sk, srcm.ravel()).reshape(K, P, CAP),
                      take(sv, srcm.ravel()).reshape(K, P, CAP),
                      take(sts, srcm.ravel()).reshape(K, P, CAP), ok)
    return zero_invalid(out)


def main():
    rng = np.random.RandomState(0)
    keys = jnp.asarray(rng.randint(0, NK, (K, P, B)), jnp.int32)
    vals = jnp.asarray(rng.randint(0, 100, (K, P, B)), jnp.int32)
    ts = jnp.asarray(rng.randint(0, 1000, (K, P, B)), jnp.int32)
    valid = jnp.broadcast_to(
        jnp.asarray(np.arange(B)[None, None, :] < 200, jnp.bool_), (K, P, B))
    batch = RecordBatch(keys, vals, ts, valid)

    timeit("sorted gather (vmap K)",
           lambda b: jax.vmap(sorted_gather)(b), batch)
    timeit("sorted scatter (vmap K)",
           lambda b: jax.vmap(sorted_scatter)(b), batch)
    timeit("block-flat one-sort gather", block_flat_gather, batch)

    ref, _ = jax.jit(lambda b: jax.vmap(
        lambda x: routing.route_hash(x, P, 64, CAP))(b))(batch)
    for name, fn in [("sorted_gather", lambda b: jax.vmap(sorted_gather)(b)),
                     ("sorted_scatter",
                      lambda b: jax.vmap(sorted_scatter)(b)),
                     ("block_flat", block_flat_gather)]:
        got = jax.jit(fn)(batch)
        match = all(np.array_equal(np.asarray(a), np.asarray(g))
                    for a, g in zip(jax.tree_util.tree_leaves(ref),
                                    jax.tree_util.tree_leaves(got)))
        print(f"{name} bit-identical: {match}")


if __name__ == "__main__":
    main()
