#!/usr/bin/env python
"""TPU correctness lane: runs the recovery property and the Pallas
histogram kernel on the REAL chip (the pytest suite forces CPU via
tests/conftest.py; this script is the driver-invokable complement so
bit-identical recovery and the Mosaic-compiled kernel are exercised on
hardware, round-3 verdict item #9).

Exit 0 = all checks passed; prints one status line per check.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def check(name, fn):
    t0 = time.monotonic()
    fn()
    print(f"PASS {name} ({time.monotonic() - t0:.1f}s)", flush=True)


def pallas_histogram_on_chip():
    import jax
    import jax.numpy as jnp
    from clonos_tpu.ops.histogram import keyed_hist
    assert jax.devices()[0].platform == "tpu", "no TPU visible"
    rng = np.random.RandomState(0)
    nk = 997
    keys = jnp.asarray(rng.randint(-3, nk + 5, (64, 8, 300)), jnp.int32)
    vals = jnp.asarray(rng.randint(-9, 9, (64, 8, 300)), jnp.int32)
    valid = jnp.asarray(rng.rand(64, 8, 300) < 0.7)
    s1, c1 = keyed_hist(keys, vals, valid, nk, force="pallas")
    s2, c2 = keyed_hist(keys, vals, valid, nk, force="xla")
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


def recovery_per_vertex_class_on_chip():
    """Bench topology (source -> window -> reduce -> sink), one failure
    per vertex class, each recovery bit-identical to a golden run —
    executed on the real chip."""
    import jax
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import canonical_carry

    def build():
        env = StreamEnvironment(name="tpu-check", num_key_groups=16,
                                default_edge_capacity=128)
        (env.synthetic_source(vocab=97, batch_size=16, parallelism=2)
            .key_by().window_count(num_keys=97, window_size=60)
            .key_by().reduce(num_keys=97)
            .sink())
        return env.build()

    def runner():
        r = ClusterRunner(build(), steps_per_epoch=4, log_capacity=1 << 9,
                          max_epochs=16, inflight_ring_steps=32, seed=3)
        r.executor.time_source.now = \
            lambda it=iter(range(0, 40000, 9)): next(it)
        return r

    for flat in (0, 3, 5, 7):            # source, window, reduce, sink
        golden = runner()
        r = runner()
        for rr in (golden, r):
            rr.run_epoch()               # completed: no pending ckpt, so
            rr.step()                    # recovery logs no IGNORE rows
            rr.step()                    # (those legitimately differ
            rr.step()                    # from a never-failed run)
        r.inject_failure([flat])
        r.recover()
        ca = canonical_carry(r.executor.carry)
        cb = canonical_carry(golden.executor.carry)
        for xa, xb in zip(jax.tree_util.tree_leaves(ca),
                          jax.tree_util.tree_leaves(cb)):
            np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
        print(f"  subtask {flat}: bit-identical on TPU", flush=True)


def main():
    check("pallas_histogram_on_chip", pallas_histogram_on_chip)
    check("recovery_per_vertex_class_on_chip",
          recovery_per_vertex_class_on_chip)
    print("ALL TPU CHECKS PASSED")


if __name__ == "__main__":
    main()
