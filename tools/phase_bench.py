#!/usr/bin/env python
"""bench.py workload with recovery phase_ms breakdown printed (what the
199s cold recovery is actually spent on)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import numpy as np


def main():
    import jax
    sys.argv = ["bench"]
    os.environ.setdefault("BENCH_STEPS_PER_EPOCH", "1024")
    import bench

    job = bench.build_job()
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP

    SPE = int(os.environ["BENCH_STEPS_PER_EPOCH"])
    need = bench.FILL_EPOCHS * SPE * DETS_PER_STEP
    cap = 1 << max(need - 1, 1).bit_length()
    runner = ClusterRunner(job, steps_per_epoch=SPE,
                          log_capacity=cap, max_epochs=16,
                          inflight_ring_steps=1 << max(
                              bench.FILL_EPOCHS * SPE, 2).bit_length(),
                          seed=7)
    t0 = time.monotonic()
    runner.run_epoch(complete_checkpoint=True)
    print("epoch0 (compile+run):", round(time.monotonic() - t0, 1), "s",
          flush=True)
    t0 = time.monotonic()
    for _ in range(bench.FILL_EPOCHS):
        runner.run_epoch(complete_checkpoint=False)
    fill = time.monotonic() - t0
    print("fill:", round(fill, 1), "s  ->",
          round(bench.FILL_EPOCHS * SPE * 8 * 128 / fill / 1e3), "k rec/s",
          flush=True)
    runner.inject_failure([9])
    t0 = time.monotonic()
    report = runner.recover()
    cold = time.monotonic() - t0
    print("cold recovery:", round(cold, 1), "s", flush=True)
    print("cluster phases:", json.dumps(
        {k: round(v, 1) for k, v in report.phase_ms.items()}), flush=True)
    print("replay phases:", json.dumps(
        {k: round(v, 1) for k, v in
         report.managers[0].result.phase_ms.items()}), flush=True)
    mgr = report.managers[0]
    t0 = time.monotonic()
    res = mgr.replayer.replay(mgr.plan)
    np.asarray(res.emit_counts)
    warm = time.monotonic() - t0
    print("warm replay:", round(warm * 1e3, 1), "ms  phases:",
          json.dumps({k: round(v, 1) for k, v in res.phase_ms.items()}),
          flush=True)


if __name__ == "__main__":
    main()
