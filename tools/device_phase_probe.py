#!/usr/bin/env python
"""Amortized device-compute cost of each warm-recovery program at bench
shapes (tunnel RTT excluded by chaining N dispatches per sync): routing,
replay block, log restore, graft, ring write, replica copy."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import bench
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP
    from clonos_tpu.utils.devsync import device_sync

    SPE = bench.STEPS_PER_EPOCH
    job = bench.build_job()
    need = bench.FILL_EPOCHS * SPE * DETS_PER_STEP
    cap = 1 << need.bit_length()
    span = bench.FILL_EPOCHS * SPE
    runner = ClusterRunner(job, steps_per_epoch=SPE, log_capacity=cap,
                           max_epochs=16,
                           inflight_ring_steps=1 << (span - 1).bit_length(),
                           recovery_block_steps=8192, block_steps=1024,
                           seed=7)
    runner.run_epoch(complete_checkpoint=True)
    for _ in range(bench.FILL_EPOCHS):
        runner.run_epoch(complete_checkpoint=False)
    device_sync(runner.executor.carry)
    print("setup done", flush=True)

    failed = bench.PAR + 1
    runner.inject_failure([failed])
    t0 = time.monotonic()
    report = runner.recover()
    print("cold recover:", round(time.monotonic() - t0, 1), "s", flush=True)

    carry = runner.executor.carry
    ch = runner._chunk()
    eidx = 0                      # source->window edge
    ri = runner.executor.compiled.ring_index[0]
    el = carry.out_rings[ri]
    z = jnp.asarray(0, jnp.int32)
    n_steps = span

    def amort(label, fn, n=8):
        fn()
        device_sync(carry)
        t1 = time.monotonic()
        for _ in range(n):
            fn()
        device_sync(carry)
        print(f"{label}: {(time.monotonic() - t1) * 1e3 / n:.1f}ms",
              flush=True)

    rt = runner._route_chunk_fn(eidx, ch)
    amort("route lane 8192 window",
          lambda: rt(el, z, jnp.asarray(1, jnp.int32), z,
                     jnp.asarray(n_steps, jnp.int32), z))
    rta = runner._route_chunk_fn(eidx, ch, all_lanes=True)
    amort("route all-lanes 8192 window",
          lambda: rta(el, z, z, jnp.asarray(n_steps, jnp.int32), z))

    mgr = report.managers[0]
    plan = mgr.plan
    t_d, r_d, e_d = plan.det_device
    state0 = jax.tree_util.tree_map(
        lambda x: x[plan.subtask][None], plan.checkpoint_op_state)
    chunk = plan.input_steps[0]
    jb = mgr.replayer._jit_block
    amort("replay block 8192",
          lambda: jb(state0, chunk, t_d[:ch], r_d[:ch],
                     jnp.asarray(1, jnp.int32), jnp.zeros((), jnp.int32)))

    me = runner.executor.compiled.max_epochs
    lr = runner._log_restore_from_replica_fn()
    amort("log restore from replica",
          lambda: lr(carry.replicas, z, z, z, z,
                     jnp.zeros((me,), jnp.int32),
                     jnp.zeros((me,), jnp.bool_), z, z))

    rw = runner._ring_write_fn(ri, ch)
    ring_dummy = jax.tree_util.tree_map(jnp.zeros_like, el)
    out_cap = runner.executor.compiled.vertex_out_capacity(0)
    from clonos_tpu.api.records import RecordBatch as RB
    zb = RB(jnp.zeros((ch, out_cap), jnp.int32),
            jnp.zeros((ch, out_cap), jnp.int32),
            jnp.zeros((ch, out_cap), jnp.int32),
            jnp.zeros((ch, out_cap), jnp.bool_))

    def ring_once():
        nonlocal ring_dummy
        ring_dummy, _ = rw(ring_dummy, zb, z, z,
                           jnp.asarray(1, jnp.int32), z)
    amort("ring write 8192 chunk (donated)", ring_once)

    nr = runner.plan.num_replicas
    rc = runner._replica_copy_fn()
    reps_dummy = jax.tree_util.tree_map(jnp.zeros_like, carry.replicas)

    def rep_once():
        nonlocal reps_dummy
        reps_dummy = rc(reps_dummy, carry.logs,
                        jnp.full((nr,), nr, jnp.int32),
                        jnp.zeros((nr,), jnp.int32))
    amort("replica copy (donated)", rep_once)

    # graft
    gf = runner._graft_fn(1)
    st_log = jax.tree_util.tree_map(lambda x: x[0],
                                    (carry.logs,))[0]
    import clonos_tpu.causal.log as clog
    one_log = jax.tree_util.tree_map(lambda x: x[0], carry.logs)
    carry_dummy = jax.tree_util.tree_map(jnp.zeros_like, carry)

    def graft_once():
        nonlocal carry_dummy
        carry_dummy = gf(carry_dummy, state0, one_log, z, z, z)
    amort("graft (donated)", graft_once)


if __name__ == "__main__":
    main()
