#!/usr/bin/env python
"""Convert flight-recorder JSON-lines to Chrome trace_event JSON.

Standalone converter over clonos_tpu.obs (the CLI's ``clonos_tpu trace
--chrome`` wraps the same functions): reads one or more
``trace-*.jsonl`` files — typically the JobMaster's and every worker's
files from one run, which share a trace id via the control-wire
propagation — validates the result, and writes a file loadable in
Perfetto (https://ui.perfetto.dev) or Chrome ``about:tracing``.

    python tools/trace2chrome.py traces/trace-*.jsonl -o out.json
    python tools/trace2chrome.py traces/trace-*.jsonl --check

``--check`` validates without writing (the tests' validity gate).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Runnable by path from anywhere: the repo root (this file's parent's
# parent) hosts the clonos_tpu package.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="+", help="trace-*.jsonl inputs")
    ap.add_argument("-o", "--out", default=None,
                    help="output Chrome trace JSON path")
    ap.add_argument("--trace-id", default=None,
                    help="keep only records of this trace id")
    ap.add_argument("--check", action="store_true",
                    help="validate only; write nothing")
    args = ap.parse_args(argv)
    if not args.check and args.out is None:
        ap.error("either --out or --check is required")

    from clonos_tpu import obs

    records = obs.load_jsonl(args.files)
    doc = obs.to_chrome(records, trace_id=args.trace_id)
    n = obs.validate_chrome(doc)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f)
    traces = sorted({r.get("trace") for r in records})
    print(json.dumps({"records": len(records), "events": n,
                      "traces": traces, "out": args.out,
                      "valid": True}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
