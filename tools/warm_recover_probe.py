#!/usr/bin/env python
"""Measure warm recovery wall + phase breakdown at full bench shapes
WITHOUT the 4-minute prewarm: pay one cold recover (compiles the failure
path), then repeat inject+recover to see the steady-state protocol cost.
Set PROBE_FILL to try larger replay spans."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import bench
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP
    from clonos_tpu.utils.devsync import device_sync

    fill = int(os.environ.get("PROBE_FILL", bench.FILL_EPOCHS))
    SPE = bench.STEPS_PER_EPOCH
    job = bench.build_job()
    need = fill * SPE * DETS_PER_STEP
    cap = 1 << need.bit_length()
    span = fill * SPE
    ring = 1 << (span - 1).bit_length()   # exactly the fill span
    print("fill:", fill, "ring_steps:", ring, "log_cap:", cap, flush=True)
    runner = ClusterRunner(job, steps_per_epoch=SPE, log_capacity=cap,
                           max_epochs=16, inflight_ring_steps=ring,
                           recovery_block_steps=8192, block_steps=1024,
                           seed=7)
    t0 = time.monotonic()
    runner.run_epoch(complete_checkpoint=True)
    device_sync(runner.executor.carry)
    print("epoch0:", round(time.monotonic() - t0, 1), "s", flush=True)
    t0 = time.monotonic()
    for _ in range(fill):
        runner.run_epoch(complete_checkpoint=False)
    device_sync(runner.executor.carry)
    print("fill:", round(time.monotonic() - t0, 1), "s", flush=True)

    failed = bench.PAR + 1
    runner.inject_failure([failed])
    t0 = time.monotonic()
    report = runner.recover()
    print("cold recover:", round(time.monotonic() - t0, 1), "s",
          {k: round(v, 1) for k, v in report.phase_ms.items()}, flush=True)

    for trial in range(4):
        runner.inject_failure([failed])
        t0 = time.monotonic()
        rep = runner.recover()
        device_sync(runner.executor.carry)
        print(f"warm recover #{trial}: "
              f"{(time.monotonic() - t0) * 1e3:.1f}ms phases:",
              {k: round(v, 1) for k, v in rep.phase_ms.items()}, flush=True)

    # warm replay alone (the vs_baseline measurement)
    mgr = report.managers[0]
    for trial in range(5):
        t1 = time.monotonic()
        result = mgr.replayer.replay(mgr.plan)
        device_sync(result.emit_counts)
        print(f"warm replay #{trial}: "
              f"{(time.monotonic() - t1) * 1e3:.1f}ms "
              f"records={result.records_replayed}", flush=True)


if __name__ == "__main__":
    main()
