#!/usr/bin/env python
"""Micro-profile of the steady-state block program's pieces at bench shapes
(K=512, P=8), plus the FULL fused block program from the bench topology —
so optimization targets the real hot spot, not a guess.

Timing method: enqueue n calls, one d2h sync at the end (block_until_ready
is unreliable on the tunneled backend), subtract a measured round-trip.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.operators import (BlockContext, SyntheticSource,
                                      TumblingWindowCountOperator,
                                      KeyedReduceOperator, SinkOperator)
from clonos_tpu.api.records import RecordBatch
from clonos_tpu.parallel import routing

K, P, B, CAP, NK = 512, 8, 128, 1024, 997


from clonos_tpu.utils.devsync import device_sync as _sync  # noqa: E402


def timeit(name, fn, *args, n=10):
    jfn = jax.jit(fn)
    out = jfn(*args)
    _sync(out)
    rts = []
    for _ in range(3):
        t0 = time.monotonic()
        _sync(out)
        rts.append(time.monotonic() - t0)
    rt = min(rts)
    t0 = time.monotonic()
    for _ in range(n):
        out = jfn(*args)
    _sync(out)
    ms = max(((time.monotonic() - t0) - rt) / n * 1e3, 0.0)
    print(f"{name:48s} {ms:9.2f} ms")
    return ms


def main():
    rng = np.random.RandomState(0)
    bctx = BlockContext(
        times=jnp.asarray(rng.randint(0, 1 << 20, K), jnp.int32),
        rng_bits=jnp.asarray(rng.randint(0, 1 << 30, K), jnp.int32),
        epoch=jnp.zeros((), jnp.int32), step0=jnp.zeros((), jnp.int32),
        subtask=jnp.arange(P, dtype=jnp.int32))

    def mkbatch(k, p, b, fill, vocab=NK):
        keys = jnp.asarray(rng.randint(0, vocab, (k, p, b)), jnp.int32)
        vals = jnp.ones((k, p, b), jnp.int32)
        ts = jnp.zeros((k, p, b), jnp.int32)
        valid = jnp.asarray(
            np.arange(b)[None, None, :] < fill, jnp.bool_)
        valid = jnp.broadcast_to(valid, (k, p, b))
        return RecordBatch(keys, vals, ts, valid)

    win = TumblingWindowCountOperator(num_keys=NK, window_size=1 << 30)
    red = KeyedReduceOperator(num_keys=NK)

    src_out = mkbatch(K, P, B, B)          # [K,P,128]
    win_in = mkbatch(K, P, CAP, 128)       # [K,P,1024], ~128 valid
    win_out = mkbatch(K, P, NK, 200)       # [K,P,997]

    plan = routing.plan_static_hash(
        np.arange(NK, dtype=np.int32), P, P, 64, CAP)
    red_in, _ = jax.jit(plan.apply)(win_out)

    timeit("window.process_block [K,P,1024]",
           lambda s, b: win.process_block(s, b, bctx),
           win.init_state(P), win_in)
    timeit("reduce.process_block dynamic [K,P,1024]",
           lambda s, b: red.process_block(s, b, bctx),
           red.init_state(P), red_in)
    timeit("reduce.process_block_static_keys",
           lambda s, b: red.process_block_static_keys(
               s, b, bctx, plan.slot_keys),
           red.init_state(P), red_in)

    timeit("route_hash_block src->win [K,P,128]->1024",
           lambda b: routing.route_hash_block(b, P, 64, CAP), src_out)
    timeit("route_hash_block win->red [K,P,997]->1024",
           lambda b: routing.route_hash_block(b, P, 64, CAP), win_out)
    timeit("static plan.apply win->red",
           lambda b: plan.apply(b), win_out)

    # --- the real thing: bench topology full block --------------------------
    sys.argv = ["profile"]
    import bench
    from clonos_tpu.runtime.executor import LocalExecutor
    job = bench.build_job()
    ex = LocalExecutor(job, steps_per_epoch=K, log_capacity=1 << 13,
                       max_epochs=16, inflight_ring_steps=1 << 10, seed=7)
    bi = ex._next_block_inputs(K)
    carry = ex.carry
    ms = timeit("FULL run_block (bench topology, K=512)",
                lambda c, i: ex.compiled.run_block(c, i), carry, bi)
    print(f"  -> steady-state ceiling ~{K * P * B / ms * 1e3 / 1e6:.2f} "
          f"M records/s")


if __name__ == "__main__":
    main()
