#!/usr/bin/env python
"""Micro-profile of the steady-state block program's pieces at bench shapes.

Times each vertex's process_block, each exchange, the ring append, and the
determinant log append in isolation (same shapes as bench.py's topology with
K=512, P=8), plus the full fused block — so optimization targets the real
hot spot, not a guess.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.operators import (BlockContext, SyntheticSource,
                                      TumblingWindowCountOperator,
                                      KeyedReduceOperator, SinkOperator)
from clonos_tpu.api.records import RecordBatch
from clonos_tpu.parallel import routing
from clonos_tpu.causal import log as clog
from clonos_tpu.inflight import log as ifl

K, P, B, CAP, NK = 512, 8, 128, 1024, 997
RING_STEPS = 4096
LOG_CAP = 1 << 14
L = 32


def _sync(tree):
    """Force real device completion (block_until_ready is a no-op on the
    tunneled backend): read one element of one leaf d2h."""
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "shape")]
    x = leaves[0]
    np.asarray(x.ravel()[0] if x.ndim else x)


def timeit(name, fn, *args, n=10):
    """Enqueue n calls, sync once at the end, subtract the measured sync
    round-trip; TPU executes the queue serially so total/n is per-call."""
    jfn = jax.jit(fn)
    out = jfn(*args)
    _sync(out)
    t0 = time.monotonic()
    _sync(out)
    rt = time.monotonic() - t0          # pure round-trip latency
    t0 = time.monotonic()
    for _ in range(n):
        out = jfn(*args)
    _sync(out)
    ms = ((time.monotonic() - t0) - rt) / n * 1e3
    print(f"{name:44s} {ms:9.2f} ms")
    return ms


def main():
    rng = np.random.RandomState(0)
    bctx = BlockContext(
        times=jnp.asarray(rng.randint(0, 1 << 20, K), jnp.int32),
        rng_bits=jnp.asarray(rng.randint(0, 1 << 30, K), jnp.int32),
        epoch=jnp.zeros((), jnp.int32), step0=jnp.zeros((), jnp.int32),
        subtask=jnp.arange(P, dtype=jnp.int32))

    def mkbatch(k, p, b, fill):
        keys = jnp.asarray(rng.randint(0, NK, (k, p, b)), jnp.int32)
        vals = jnp.ones((k, p, b), jnp.int32)
        ts = jnp.zeros((k, p, b), jnp.int32)
        valid = jnp.asarray(
            np.arange(b)[None, None, :] < fill, jnp.bool_)
        valid = jnp.broadcast_to(valid, (k, p, b))
        return RecordBatch(keys, vals, ts, valid)

    src = SyntheticSource(vocab=NK, batch_size=B)
    win = TumblingWindowCountOperator(num_keys=NK, window_size=1 << 30)
    red = KeyedReduceOperator(num_keys=NK)
    snk = SinkOperator()

    src_state = src.init_state(P)
    win_state = win.init_state(P)
    red_state = red.init_state(P)
    snk_state = snk.init_state(P)

    src_out = mkbatch(K, P, B, B)          # [K,P,128]
    win_in = mkbatch(K, P, CAP, 128)       # [K,P,1024], ~128 valid
    win_out = mkbatch(K, P, NK, 200)       # [K,P,997]
    red_in = mkbatch(K, P, CAP, 200)

    timeit("source.process_block", lambda s: src.process_block(s, None, bctx),
           src_state)
    timeit("window.process_block", lambda s, b: win.process_block(s, b, bctx),
           win_state, win_in)
    timeit("reduce.process_block", lambda s, b: red.process_block(s, b, bctx),
           red_state, red_in)
    timeit("sink.process_block", lambda s, b: snk.process_block(s, b, bctx),
           snk_state, red_in)

    timeit("route_hash src->win [K,P,128]->1024",
           lambda b: jax.vmap(lambda x: routing.route_hash(
               x, P, 64, CAP))(b), src_out)
    timeit("route_hash win->red [K,P,997]->1024",
           lambda b: jax.vmap(lambda x: routing.route_hash(
               x, P, 64, CAP))(b), win_out)
    timeit("route_forward red->sink",
           lambda b: jax.vmap(lambda x: routing.route_forward(
               x, CAP))(b), red_in)

    ring = ifl.create(RING_STEPS, P, NK, 16)
    timeit("ring append [4096,8,997] no-donate",
           lambda r, b: ifl.append_block(r, b), ring, win_out)

    logs = jax.vmap(lambda _: clog.create(LOG_CAP, 16))(jnp.arange(L))
    rows = jnp.zeros((L, K * 4, 8), jnp.int32)
    timeit("clog.v_append_full [32,2048,8]",
           lambda l, r: clog.v_append_full(l, r), logs, rows)
    R = 192
    reps = jax.vmap(lambda _: clog.create(LOG_CAP, 16))(jnp.arange(R))
    rrows = jnp.zeros((R, K * 4, 8), jnp.int32)
    timeit(f"replica v_append_full [{R},2048,8]",
           lambda l, r: clog.v_append_full(l, r), reps, rrows)


if __name__ == "__main__":
    main()
