#!/usr/bin/env python
"""Find the fast TPU formulation of the exchange (route_hash).

Current: flatten, argsort by target (stable), compute run positions,
SCATTER into [targets, capacity]. Scatters serialize on TPU; candidates
below replace the scatter with gathers and/or the argsort with a
counting-rank + co-sort.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.records import RecordBatch, zero_invalid
from clonos_tpu.parallel import routing

K, P, B, CAP, NK = 512, 8, 997, 1024, 997


def _sync(tree):
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "shape")]
    x = leaves[0]
    np.asarray(x.ravel()[0] if x.ndim else x)


def timeit(name, fn, *args, n=10):
    jfn = jax.jit(fn)
    out = jfn(*args)
    _sync(out)
    t0 = time.monotonic()
    _sync(out)
    rt = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(n):
        out = jfn(*args)
    _sync(out)
    ms = ((time.monotonic() - t0) - rt) / n * 1e3
    print(f"{name:46s} {ms:9.2f} ms")
    return ms


def current(batch):
    return jax.vmap(lambda x: routing.route_hash(x, P, 64, CAP))(batch)


def gather_exchange(batch: RecordBatch, parallelism: int, num_key_groups: int,
                    out_capacity: int):
    """Sort-then-GATHER: co-sort all lanes by target in one lax.sort, then
    build the output by gathering run_start[t]+j — no scatter anywhere."""
    kg = routing.key_group(batch.keys, num_key_groups)
    target = routing.subtask_for_key_group(kg, parallelism, num_key_groups)
    n = batch.keys.size
    flat = lambda x: x.reshape((n,))
    tgt = jnp.where(flat(batch.valid), flat(target), parallelism)
    st, sk, sv, sts = jax.lax.sort(
        (tgt, flat(batch.keys), flat(batch.values), flat(batch.timestamps)),
        num_keys=1, is_stable=True)
    run_start = jnp.searchsorted(
        st, jnp.arange(parallelism + 1, dtype=st.dtype),
        side="left").astype(jnp.int32)
    j = jnp.arange(out_capacity, dtype=jnp.int32)
    src = run_start[:parallelism, None] + j[None, :]       # [P, cap]
    ok = src < run_start[1:, None]
    srcc = jnp.minimum(src, n - 1)
    out = RecordBatch(sk[srcc], sv[srcc], sts[srcc], ok)
    run_len = run_start[1:] - run_start[:parallelism]
    dropped = jnp.maximum(run_len - out_capacity, 0)
    return zero_invalid(out), dropped


def gather_vm(batch):
    return jax.vmap(lambda x: gather_exchange(x, P, 64, CAP))(batch)


def sort_only(batch: RecordBatch):
    """Isolate the sort cost."""
    n = batch.keys.size
    flat = lambda x: x.reshape((n,))
    kg = routing.key_group(batch.keys, 64)
    target = routing.subtask_for_key_group(kg, P, 64)
    tgt = jnp.where(flat(batch.valid), flat(target), P)
    return jax.lax.sort(
        (tgt, flat(batch.keys), flat(batch.values), flat(batch.timestamps)),
        num_keys=1, is_stable=True)


def argsort_only(batch: RecordBatch):
    n = batch.keys.size
    flat = lambda x: x.reshape((n,))
    kg = routing.key_group(batch.keys, 64)
    target = routing.subtask_for_key_group(kg, P, 64)
    tgt = jnp.where(flat(batch.valid), flat(target), P)
    order = jnp.argsort(tgt, stable=True)
    return tgt[order], flat(batch.keys)[order], flat(batch.values)[order], \
        flat(batch.timestamps)[order]


def scatter_only(batch: RecordBatch):
    """Isolate the scatter cost (positions via cumsum-onehot, no sort)."""
    n = batch.keys.size
    flat = lambda x: x.reshape((n,))
    kg = routing.key_group(batch.keys, 64)
    target = routing.subtask_for_key_group(kg, P, 64)
    tgt = jnp.where(flat(batch.valid), flat(target), P)
    onehot = (tgt[:, None] == jnp.arange(P + 1, dtype=jnp.int32)[None, :]
              ).astype(jnp.int32)
    pos = (jnp.cumsum(onehot, axis=0) - onehot)[
        jnp.arange(n), jnp.clip(tgt, 0, P)]
    keep = (tgt < P) & (pos < CAP)
    row = jnp.where(keep, tgt, P)
    col = jnp.where(keep, pos, 0)
    shape = (P + 1, CAP)
    out = RecordBatch(
        keys=jnp.zeros(shape, jnp.int32).at[row, col].set(
            flat(batch.keys), mode="drop"),
        values=jnp.zeros(shape, jnp.int32).at[row, col].set(
            flat(batch.values), mode="drop"),
        timestamps=jnp.zeros(shape, jnp.int32).at[row, col].set(
            flat(batch.timestamps), mode="drop"),
        valid=jnp.zeros(shape, jnp.bool_).at[row, col].set(keep, mode="drop"))
    return out


def main():
    rng = np.random.RandomState(0)
    keys = jnp.asarray(rng.randint(0, NK, (K, P, B)), jnp.int32)
    vals = jnp.ones((K, P, B), jnp.int32)
    ts = jnp.zeros((K, P, B), jnp.int32)
    valid = jnp.broadcast_to(
        jnp.asarray(np.arange(B)[None, None, :] < 200, jnp.bool_), (K, P, B))
    batch = RecordBatch(keys, vals, ts, valid)

    timeit("current route_hash (argsort+scatter)", current, batch)
    timeit("argsort+4 gathers only", lambda b: jax.vmap(argsort_only)(b),
           batch)
    timeit("lax.sort co-sort only", lambda b: jax.vmap(sort_only)(b), batch)
    timeit("scatter only (cumsum-onehot pos)",
           lambda b: jax.vmap(scatter_only)(b), batch)
    timeit("gather exchange (co-sort + gather)", gather_vm, batch)

    # correctness check vs current
    (r0, d0) = current(batch)
    (r1, d1) = gather_vm(batch)
    for a, b in zip(jax.tree_util.tree_leaves(r0),
                    jax.tree_util.tree_leaves(r1)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), "mismatch"
    assert np.array_equal(np.asarray(d0), np.asarray(d1))
    print("gather exchange bit-identical to current: OK")


if __name__ == "__main__":
    main()
