#!/usr/bin/env python
"""Round 3: dynamic histogram (contrib) formulations for the window/reduce
blocks — the scatter-add replacement."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=10):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def contrib_scatter(keys, vals, valid, nk):
    K, p, _ = keys.shape
    step = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None, None],
                            keys.shape)
    sub = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32)[None, :, None],
                           keys.shape)
    return jnp.zeros((K, p, nk), jnp.int32).at[step, sub, keys].add(
        jnp.where(valid, vals, 0), mode="drop")


def contrib_chunked_cmp(keys, vals, valid, nk, chunk=128):
    # acc += sum over chunk records of (key==n)*v, one [K,P,chunk,nk]
    # fused compare-mask-reduce per chunk (no scatter, no sort).
    K, p, B = keys.shape
    v = jnp.where(valid, vals, 0)
    iota = jnp.arange(nk, dtype=jnp.int32)
    acc = jnp.zeros((K, p, nk), jnp.int32)
    for lo in range(0, B, chunk):
        kc = keys[:, :, lo:lo + chunk]                 # [K,P,c]
        vc = v[:, :, lo:lo + chunk]
        oh = (kc[..., None] == iota)                    # [K,P,c,nk]
        acc = acc + jnp.sum(jnp.where(oh, vc[..., None], 0), axis=2)
    return acc


def contrib_onehot_dot(keys, vals, valid, nk):
    K, p, B = keys.shape
    v = jnp.where(valid, vals, 0).astype(jnp.float32)
    oh = jax.nn.one_hot(keys, nk, dtype=jnp.float32)
    out = jnp.einsum("kpb,kpbn->kpn", v, oh,
                     preferred_element_type=jnp.float32)
    return out.astype(jnp.int32)


def main():
    print("device:", jax.devices()[0].platform)
    rng = np.random.RandomState(0)
    nk = 997
    for (K, P, B, fill) in [(512, 8, 1024, 0.125), (512, 1, 1024, 0.125),
                            (512, 8, 128, 1.0)]:
        keys = jnp.asarray(rng.randint(0, nk, (K, P, B)), jnp.int32)
        vals = jnp.ones((K, P, B), jnp.int32)
        valid = jnp.asarray(rng.rand(K, P, B) < fill)
        fns = {
            "scatter": jax.jit(lambda k, v, m: contrib_scatter(k, v, m, nk)),
            "chunk128": jax.jit(
                lambda k, v, m: contrib_chunked_cmp(k, v, m, nk, 128)),
            "chunk256": jax.jit(
                lambda k, v, m: contrib_chunked_cmp(k, v, m, nk, 256)),
            "onehot_dot": jax.jit(
                lambda k, v, m: contrib_onehot_dot(k, v, m, nk)),
        }
        ref = None
        line = f"[{K},{P},{B}] fill={fill}: "
        for name, fn in fns.items():
            t, out = timeit(fn, keys, vals, valid)
            if ref is None:
                ref = out
            ok = bool(jnp.array_equal(ref, out))
            line += f"{name} {t*1e3:.1f}ms(eq={ok}) "
        print(line)

    # window-block-like pipeline: contrib -> cumsum -> take_along (fused)
    K, P, B = 512, 8, 1024
    keys = jnp.asarray(rng.randint(0, nk, (K, P, B)), jnp.int32)
    vals = jnp.ones((K, P, B), jnp.int32)
    valid = jnp.asarray(rng.rand(K, P, B) < 0.125)

    def pipeline(contrib_fn):
        def f(k, v, m):
            c = contrib_fn(k, v, m, nk)
            cum = jnp.cumsum(c, axis=0)
            out = jnp.take_along_axis(
                cum.reshape(K * P, nk), k.reshape(K * P, B), axis=1)
            return out.reshape(K, P, B)
        return jax.jit(f)

    t1, r1 = timeit(pipeline(contrib_scatter), keys, vals, valid)
    t2, r2 = timeit(pipeline(
        lambda k, v, m, n_: contrib_chunked_cmp(k, v, m, n_, 128)),
        keys, vals, valid)
    print(f"pipeline scatter {t1*1e3:.1f}ms  chunk128 {t2*1e3:.1f}ms  "
          f"eq={bool(jnp.array_equal(r1, r2))}")


if __name__ == "__main__":
    main()
