#!/usr/bin/env python
"""Marker lint: every ``pytest.mark.<name>`` in tests/ must be either a
pytest builtin or registered in REGISTERED_MARKERS (which
tests/conftest.py registers with pytest at configure time, keeping this
file the single source of truth). Unregistered markers are silent
no-ops under ``-m`` filters — a test tagged with a typo'd ``slow``
would run in tier-1 forever — so the lint runs inside pytest_configure
and fails the session loudly.

Standalone: ``python tools/check_markers.py`` exits 1 listing
violations.
"""

import os
import re
import sys

# Markers this repo registers (tier-1 deselects `slow`).
REGISTERED_MARKERS = {
    "slow": "long-running test, excluded from the tier-1 gate "
            "(-m 'not slow')",
}

# Pytest's own markers — always legal, never need registration.
BUILTIN_MARKERS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings",
}

_MARK_RE = re.compile(r"\bpytest\.mark\.([A-Za-z_]\w*)")


def check(tests_dir):
    """Scan ``tests_dir`` for marker uses; return a list of
    '<file>:<line>: unregistered marker <name>' violations."""
    allowed = BUILTIN_MARKERS | set(REGISTERED_MARKERS)
    violations = []
    for fn in sorted(os.listdir(tests_dir)):
        if not fn.endswith(".py"):
            continue
        path = os.path.join(tests_dir, fn)
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                for m in _MARK_RE.finditer(line):
                    name = m.group(1)
                    if name not in allowed:
                        violations.append(
                            f"{os.path.join('tests', fn)}:{lineno}: "
                            f"unregistered marker {name!r}")
    return violations


def main(argv=None):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests_dir = os.path.join(root, "tests")
    violations = check(tests_dir)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"{len(violations)} unregistered marker use(s); register "
              f"in tools/check_markers.py:REGISTERED_MARKERS",
              file=sys.stderr)
        return 1
    print(f"markers ok ({len(REGISTERED_MARKERS)} registered)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
