#!/usr/bin/env python
"""Shim over ``clonos_tpu.lint.markers`` (the ``replay_dissect`` ->
``dissect`` precedent): the marker registry and the scan both moved
into the lint package as the ``markers`` rule, where
``clonos_tpu lint tests/`` and tests/conftest.py share them. This file
keeps the historical entry point — ``python tools/check_markers.py``
still exits 1 listing violations — and the historical import surface
(REGISTERED_MARKERS / BUILTIN_MARKERS / check).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from clonos_tpu.lint.markers import (BUILTIN_MARKERS,     # noqa: E402,F401
                                     REGISTERED_MARKERS, check)


def main(argv=None):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = check(os.path.join(root, "tests"))
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"{len(violations)} unregistered marker use(s); register "
              f"in clonos_tpu/lint/markers.py:REGISTERED_MARKERS",
              file=sys.stderr)
        return 1
    print(f"markers ok ({len(REGISTERED_MARKERS)} registered)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
