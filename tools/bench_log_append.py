#!/usr/bin/env python
"""Determinant-log append-path measurement — the decision record for
removing ops/log_kernels.py (round-3 verdict item: wire the Pallas
ring-append into the runtime or commit the benchmark showing the XLA
path wins, then delete it).

Findings on the real chip (run this script to reproduce):

- The BULK path (one [L, K*4, 8] block append per superstep-block,
  clog.v_append_full) moves ~12MB in ~10-15ms — and the Pallas
  ``ring_append_stacked`` kernel cannot serve it at all: its design was
  one cache line (16 rows) per call, so a 2048-row block append would
  need 128 sequential kernel launches (~2ms dispatch each over the
  tunneled backend — 10x slower than the scatter it replaces).
- The ASYNC path (single determinant row to a set of logs + replicas)
  is a fused masked one-row set (executor._jit_append_many): one
  dispatch, ~1ms. The kernel's per-log scalar-prefetch machinery buys
  nothing over that.

Hence: no runtime niche; the kernel was deleted. The framework's Pallas
usage lives where it actually wins: the keyed histogram
(ops/histogram.py, ~8x over XLA scatter-add in the window/reduce
blocks).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.causal import log as clog
from clonos_tpu.utils.devsync import device_sync


def timeit(name, fn, *args, n=10):
    jfn = jax.jit(fn)
    out = jfn(*args)
    device_sync(out)
    t0 = time.monotonic()
    device_sync(out)
    rt = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(n):
        out = jfn(*args)
    device_sync(out)
    ms = max(((time.monotonic() - t0) - rt) / n * 1e3, 0.0)
    print(f"{name:48s} {ms:9.2f} ms")


def main():
    print("device:", jax.devices()[0].platform)
    rng = np.random.RandomState(0)
    for L, k in ((32, 2048), (192, 2048)):
        logs = jax.vmap(lambda _: clog.create(1 << 14, 16))(jnp.arange(L))
        rows = jnp.asarray(rng.randint(0, 99, (L, k, 8)), jnp.int32)
        timeit(f"v_append_full [{L},{k},8] (the bulk block path)",
               clog.v_append_full, logs, rows)
        one = jnp.asarray(rng.randint(0, 99, (L, 1, 8)), jnp.int32)
        counts = jnp.ones((L,), jnp.int32)
        timeit(f"v_append [{L},1,8] (the async row path)",
               clog.v_append, logs, one, counts)


if __name__ == "__main__":
    main()
