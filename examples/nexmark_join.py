"""NEXMark-style auction/bid join with external causal-service calls
(BASELINE config #5 shape: flink-table join machinery + the reference
README's CausalSerializableService example, re-imagined dense).

Run:
    python -m clonos_tpu run examples.nexmark_join:build_job --epochs 2
"""

import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from clonos_tpu.api.environment import StreamEnvironment

KEYS = 499


def build_job(parallelism: int = 8):
    env = StreamEnvironment(name="nexmark-join", num_key_groups=128,
                            default_edge_capacity=256)
    auctions = env.synthetic_source(vocab=KEYS, batch_size=64,
                                    parallelism=parallelism, name="auctions")
    bids = env.synthetic_source(vocab=KEYS, batch_size=64,
                                parallelism=parallelism, name="bids")
    joined = auctions.key_by().join(
        bids.key_by(), num_keys=KEYS, window=8, interval=1 << 30,
        name="auction-bid-join")
    joined.sink(name="results")
    return env.build()


def main():
    from clonos_tpu.causal import determinant as det
    from clonos_tpu.runtime.cluster import ClusterRunner

    runner = ClusterRunner(build_job(parallelism=4), steps_per_epoch=8)
    # External-service calls through the causal wrapper (logged + replayed).
    store = det.SidecarStore(owner=1)
    fx = runner.executor.service_factory(
        8, store).serializable_service(lambda req: b"rate:" + req)
    runner.run_epoch()
    print("fx lookup:", fx.apply(b"USD-EUR"))
    runner.run_epoch()
    print("join ran 2 epochs;",
          int(runner.executor.log_sizes().sum()), "determinant rows logged")


if __name__ == "__main__":
    main()
