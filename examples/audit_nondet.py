"""Audit-bait job: spanning.py with an UNLOGGED nondeterministic map.

``salt`` perturbs record VALUES with a module-level random constant
drawn at import time — a stand-in for the classic exactly-once bug: an
operator consulting state outside the causal log (an unlogged RNG draw,
a wall clock, an env var). Replay after a process kill re-imports this
module, draws a fresh SALT, and reproduces every key, count, window
total and determinant row — only the record VALUES crossing the hash
exchange differ. None of the framework's structural recovery checks can
see that; the per-epoch audit digests (obs/audit.py fingerprint ring
contents) are exactly what catches it, so the divergence test drives
THIS job and asserts a ``recovery.audit.divergence`` on a ``ring/*``
channel.

Keys and counts stay deterministic on purpose: the job must pass every
pre-audit recovery invariant (det-stream equality, output-cut counts,
state digests) and fail ONLY the audit.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from clonos_tpu.api.environment import StreamEnvironment

VOCAB = 256
WINDOW_MS = 500
BATCH = 8

# The nondeterminism: fresh per process, NOT recorded as a determinant.
# 3 bytes keeps the salted arithmetic comfortably inside the int32
# record lanes while a cross-process collision stays a 2^-24 fluke.
SALT = 1 + int.from_bytes(os.urandom(3), "little")


def build_job():
    """lines -> tag -> (HASH) -> salt -> window -> sink.

    The first HASH exchange is still the unique slice boundary, so a
    two-worker slot-pool placement splits ``[lines, tag]`` from
    ``[salt, window, sink]`` exactly like spanning.py — killing the
    second worker replays ``salt`` under a different SALT."""
    env = StreamEnvironment(name="audit-nondet", num_key_groups=64)
    (env.host_source(batch_size=BATCH, parallelism=1, name="lines")
        .map(lambda k, v, t: (k % VOCAB, v, t), name="tag")
        .key_by()
        .map(lambda k, v, t: (k, (v * 31 + SALT) % 9973, t), name="salt")
        .key_by()
        .window_count(num_keys=VOCAB, window_size=WINDOW_MS, name="window")
        .sink(name="sink"))
    return env.build()
