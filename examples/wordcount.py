"""SocketWindowWordCount, TPU-native.

The reference's demo job (flink-examples-streaming
.../socket/SocketWindowWordCount.java, and the causal-services variant in
the reference README.md:46-77): words from a socket (or a synthetic
generator), keyed tumbling-window counts, printed at the sink.

Run:
    python -m clonos_tpu run examples.wordcount:build_job --epochs 4
    python examples/wordcount.py            # self-driving demo with a
                                            # mid-run failure + recovery
"""

import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from clonos_tpu.api.environment import StreamEnvironment

VOCAB = 1000
WINDOW_MS = 500


def build_job():
    env = StreamEnvironment(name="socket-window-wordcount",
                            num_key_groups=64)
    (env.synthetic_source(vocab=VOCAB, batch_size=64, parallelism=4,
                          name="words")
        .key_by()
        .window_count(num_keys=VOCAB, window_size=WINDOW_MS, name="window")
        .sink(name="print"))
    return env.build()


def build_socket_job(host: str = "localhost", port: int = 9999):
    """The literal socket variant: feed lines 'key[:value]' over TCP."""
    env = StreamEnvironment(name="socket-window-wordcount",
                            num_key_groups=64)
    (env.host_source(batch_size=64, parallelism=1, name="socket")
        .key_by()
        .window_count(num_keys=VOCAB, window_size=WINDOW_MS, name="window")
        .sink(name="print"))
    return env.build()


def main():
    import numpy as np
    from clonos_tpu.runtime.cluster import ClusterRunner

    runner = ClusterRunner(build_job(), steps_per_epoch=8)
    print("running 2 epochs + a few mid-epoch steps...")
    runner.run_epoch()
    runner.run_epoch()
    for _ in range(5):                   # mid-epoch: the failure loses
        runner.step()                    # un-checkpointed work to replay
    print(f"records so far: "
          f"{int(np.sum(np.asarray(runner.executor.carry.record_counts)))}")

    print("killing the window operator's subtask 1...")
    runner.inject_failure([5])           # window vertex (id 1), subtask 1
    report = runner.recover()
    print(f"recovered: replayed {report.steps_replayed} supersteps / "
          f"{report.records_replayed} records in {report.recovery_ms:.0f} ms")

    runner.run_epoch()
    print("post-recovery epoch ran; metrics:")
    import json
    print(json.dumps(runner.metrics.snapshot(), indent=2, default=str))


if __name__ == "__main__":
    main()
