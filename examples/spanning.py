"""Spanning-job demo: one job deployed across SEVERAL worker processes.

The SocketWindowWordCount shape (examples/wordcount.py) arranged for the
slot-pool scheduler (runtime/scheduler.py): a socket-fed source slice and
a keyed-window slice, cut on the HASH exchange between them — the shape
the reference deploys across TaskManagers (one TaskDeploymentDescriptor
per slot, SlotPool.java allocation). With two slot workers the scheduler
places ``[lines, tag]`` on one process and ``[window, sink]`` on the
other; records cross between them over the edge-export wire.

Run (three terminals; the feed is any line server on :9999, e.g. ``nc``):
    python -m clonos_tpu slotworker --jm HOST:PORT --executor-id a
    python -m clonos_tpu slotworker --jm HOST:PORT --executor-id b
    # then drive SlotPoolScheduler.deploy() against the same JobMaster
"""

import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from clonos_tpu.api.environment import StreamEnvironment

VOCAB = 256
WINDOW_MS = 500
BATCH = 8


def build_job():
    """lines -> tag -> (HASH) -> window -> sink.

    ``lines`` is a HostFeedSource at parallelism 1 (externally fed — a
    SocketFeedReader in the distributed tests); ``tag`` rides the same
    slice on a FORWARD edge; the key_by HASH exchange is the only legal
    slice boundary, so two workers always split exactly there."""
    env = StreamEnvironment(name="spanning-wordcount", num_key_groups=64)
    (env.host_source(batch_size=BATCH, parallelism=1, name="lines")
        .map(lambda k, v, t: (k % VOCAB, v, t), name="tag")
        .key_by()
        .window_count(num_keys=VOCAB, window_size=WINDOW_MS, name="window")
        .sink(name="sink"))
    return env.build()
