"""Batched-over-steps kernel costs: routing, segment-sums at block scale."""
import time
import jax, jax.numpy as jnp
import numpy as np

def bench(label, fn, *args, n=3, per=1):
    r = jax.block_until_ready(fn(*args))
    t0 = time.monotonic()
    for _ in range(n):
        r = fn(*args)
    jax.block_until_ready(r)
    dt = (time.monotonic() - t0) / n
    print(f"{label}: {dt*1e3:.2f} ms  ({dt/per*1e6:.1f} us/step)")
    return dt

KK = 512           # steps per block
N = 8192           # records per step (flattened)
T = 8
CAP = 1024
K = 997

key = jax.random.PRNGKey(0)
tgt = jax.random.randint(key, (KK, N), 0, T, jnp.int32)
vals = jnp.ones((KK, N), jnp.int32)
keys_b = jax.random.randint(key, (KK, T, 128), 0, K, jnp.int32)  # [K,P,B]

# A. batched argsort routing
@jax.jit
def route_sort(tgt):
    return jnp.argsort(tgt, axis=1, stable=True)
bench(f"batched argsort [{KK},{N}]", route_sort, tgt, per=KK)

# B. batched cumsum+unique scatter
@jax.jit
def route_cs(tgt, vals):
    oh = (tgt[..., None] == jnp.arange(T)[None, None, :]).astype(jnp.int32)
    pos = jnp.cumsum(oh, axis=1)                    # [KK, N, T]
    p = jnp.take_along_axis(pos, tgt[..., None], axis=2)[..., 0] - 1
    keep = p < CAP
    row = jnp.where(keep, tgt, T)
    col = jnp.where(keep, p, 0)
    step = jnp.broadcast_to(jnp.arange(KK)[:, None], (KK, N))
    out = jnp.zeros((KK, T + 1, CAP), jnp.int32).at[
        step, row, col].set(vals, mode="drop", unique_indices=True)
    return out
bench(f"batched cumsum-route [{KK},{N}]", route_cs, tgt, vals, per=KK)

# C. per-(step,subtask) scatter-add contributions [KK,P,B] -> [KK,P,K]
@jax.jit
def contribs_scatter(keys_b):
    z = jnp.zeros((KK, T, K), jnp.int32)
    step = jnp.broadcast_to(jnp.arange(KK)[:, None, None], keys_b.shape)
    sub = jnp.broadcast_to(jnp.arange(T)[None, :, None], keys_b.shape)
    return z.at[step, sub, keys_b].add(1, mode="drop")
bench(f"per-step contribs scatter [{KK},8,128]->[{KK},8,{K}]",
      contribs_scatter, keys_b, per=KK)

# D. prefix over steps: cumsum [KK, T, K]
c = jnp.ones((KK, T, K), jnp.int32)
@jax.jit
def prefix(c):
    return jnp.cumsum(c, axis=0)
bench(f"cumsum over steps [{KK},8,{K}]", prefix, c, per=KK)

# E. segment boundary: running acc with resets via cummax trick
fire = (jnp.arange(KK) % 97 == 0)
@jax.jit
def seg(c, fire):
    cum = jnp.cumsum(c, axis=0)
    step_id = jnp.arange(KK)
    last_reset = jax.lax.associative_scan(jnp.maximum,
                                          jnp.where(fire, step_id, -1))
    base = jnp.where(last_reset[:, None, None] >= 0,
                     cum[jnp.clip(last_reset, 0, KK - 1)], 0)
    return cum - base
bench("segmented cumsum w/ resets", seg, c, fire, per=KK)

# F. bulk det-block build+append for a block: [L,4*KK,8] -> ring [L,32768,8]
L = 32
ring = jnp.zeros((L, 32768, 8), jnp.int32)
blk = jnp.ones((L, 4 * KK, 8), jnp.int32)
@jax.jit
def bulk(ring, blk, head):
    idx = (head + jnp.arange(4 * KK)) & 32767
    return ring.at[:, idx].set(blk, unique_indices=True)
bench("bulk log append [32,2048,8]", bulk, ring, blk, jnp.asarray(0, jnp.int32), per=KK)

# G. replica bulk: gather 384 owners + scatter
own = jnp.asarray(np.random.randint(0, L, 384), jnp.int32)
rep = jnp.zeros((384, 32768, 8), jnp.int32)
@jax.jit
def bulk_rep(rep, blk, head):
    r = blk[own]
    idx = (head + jnp.arange(4 * KK)) & 32767
    return rep.at[:, idx].set(r, unique_indices=True)
bench("bulk replica append [384,2048,8]", bulk_rep, rep, blk,
      jnp.asarray(0, jnp.int32), per=KK)

# H. full source generation for a block [KK, P, B]
@jax.jit
def gen(seq0):
    lane = jnp.arange(128)
    step = jnp.arange(KK)
    seq = seq0[None, :, None] + step[:, None, None] * 128 + lane[None, None, :]
    u = seq.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
    return (u % jnp.uint32(997)).astype(jnp.int32)
bench(f"source gen [{KK},8,128]", gen, jnp.zeros((T,), jnp.int32), per=KK)
