"""Isolate: carry-copy vs per-kernel overhead inside lax.scan on this TPU."""
import time
import jax, jax.numpy as jnp
import numpy as np

def bench_scan(label, body, carry0, steps=64, n=3):
    @jax.jit
    def run(c):
        return jax.lax.scan(lambda c, _: (body(c), ()), c, None, length=steps)[0]
    r = jax.block_until_ready(run(carry0))
    t0 = time.monotonic()
    for _ in range(n):
        r = run(r)
    jax.block_until_ready(r)
    dt = (time.monotonic() - t0) / n / steps
    print(f"{label}: {dt*1e6:.1f} us/step")
    return dt

# 1. DUS into rings of different sizes, index from side counter
for S in (128, 512, 2048):
    ring0 = (jnp.zeros((S, 8, 1024), jnp.int32), jnp.zeros((), jnp.int32))
    def dus(s, S=S):
        ring, i = s
        blk = jnp.full((1, 8, 1024), i, jnp.int32)
        return (jax.lax.dynamic_update_slice(ring, blk, (i % S, 0, 0)), i + 1)
    bench_scan(f"DUS [1,8,1024] into [{S},8,1024] ({S*8*4}KB)", dus, ring0)

# 2. tiny scalar-ish body vs N chained small scatters into [1024]
for k in (1, 2, 4, 8):
    def many(s, k=k):
        acc, i = s
        for j in range(k):
            acc = acc.at[(i + j) % 1024].add(1)
        return (acc, i + 1)
    bench_scan(f"{k} chained 1-elt scatters into [1024]",
               many, (jnp.zeros((1024,), jnp.int32), jnp.zeros((), jnp.int32)),
               steps=128)

# 3. k independent elementwise ops on [8,128] arrays
for k in (1, 4, 16):
    def body(s, k=k):
        arrs, i = s
        arrs = tuple(a * 3 + i for a in arrs)
        return (arrs, i + 1)
    arrs0 = tuple(jnp.ones((8, 128), jnp.int32) for _ in range(k))
    bench_scan(f"{k} elementwise [8,128] muls", body,
               (arrs0, jnp.zeros((), jnp.int32)), steps=128)

# 4. one big fused matmul per step: [128,128]@[128,128]
m0 = jnp.eye(128, dtype=jnp.float32)
def mm(s):
    m, i = s
    return (m @ m0 + 1.0, i + 1)
bench_scan("matmul 128x128", mm, (m0, jnp.zeros((), jnp.int32)), steps=128)

# 5. matmul 1024x1024
b0 = jnp.ones((1024, 1024), jnp.bfloat16)
def mm2(s):
    m, i = s
    return ((m @ b0 * 0.001).astype(jnp.bfloat16), i + 1)
bench_scan("matmul 1024x1024 bf16", mm2, (b0, jnp.zeros((), jnp.int32)), steps=128)
