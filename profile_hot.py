"""Profile the superstep / scan / checkpoint / replay pieces in isolation."""
import os, time, json
import numpy as np
import jax, jax.numpy as jnp

STEPS = int(os.environ.get("P_STEPS", 64))

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.runtime.cluster import ClusterRunner
from clonos_tpu.runtime.executor import DETS_PER_STEP, StepInputs

env = StreamEnvironment(name="prof", num_key_groups=64,
                        default_edge_capacity=1024)
(env.synthetic_source(vocab=997, batch_size=128, parallelism=8)
    .key_by().window_count(num_keys=997, window_size=1 << 30, name="window")
    .key_by().reduce(num_keys=997, name="reduce").sink())
job = env.build()

need = 2 * STEPS * DETS_PER_STEP
cap = 1 << max(need - 1, 1).bit_length()
runner = ClusterRunner(job, steps_per_epoch=STEPS, log_capacity=cap,
                       max_epochs=16,
                       inflight_ring_steps=1 << max(2 * STEPS, 2).bit_length(),
                       seed=7)
ex = runner.executor

def t(label, fn, n=1):
    fn()  # warm
    t0 = time.monotonic()
    for _ in range(n):
        r = fn()
    jax.block_until_ready(r) if r is not None else None
    dt = (time.monotonic() - t0) / n
    print(f"{label}: {dt*1e3:.2f} ms")
    return dt

# 1. single jitted superstep
inp = ex._next_inputs()
def one_step():
    c, o = ex._jit_step(ex.carry, inp)
    jax.block_until_ready(c.record_counts)
    return None
t("superstep (single call, warm)", one_step, n=10)

# 2. input staging for an epoch
def stage():
    ins = [ex._next_inputs() for _ in range(STEPS)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *ins)
    jax.block_until_ready(stacked.time)
    return None
t(f"stage {STEPS} StepInputs", stage, n=3)

# 3. scanned epoch
ins = [ex._next_inputs() for _ in range(STEPS)]
stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *ins)
def scan_epoch():
    c, o = ex._jit_scan(ex.carry, stacked)
    jax.block_until_ready(c.record_counts)
    return None
dt = t(f"scan {STEPS} steps (warm)", scan_epoch, n=3)
print(f"  -> {dt/STEPS*1e6:.0f} us/step;"
      f" {STEPS*8*128/dt:.0f} rec/s")

# 4. roll + trunc
def roll():
    c = ex._jit_roll(ex.carry, 3)
    jax.block_until_ready(c.record_counts)
    return None
t("epoch roll (catch-up + fences)", roll, n=3)

# 5. checkpoint trigger (device_get + pickle)
def trig():
    runner.coordinator.trigger(90, ex.carry, async_write=False)
    return None
t("checkpoint trigger (full-carry pickle)", trig, n=1)

# 6. replay scan
runner.run_epoch(complete_checkpoint=True)
runner.run_epoch(complete_checkpoint=False)
runner.run_epoch(complete_checkpoint=False)
runner.inject_failure([8 + 1])
rep = runner.recover()
mgr = rep.managers[0]
def replay():
    r = mgr.replayer.replay(mgr.plan)
    jax.block_until_ready(r.emit_counts)
    return None
dt = t(f"replay ({rep.steps_replayed} steps, warm)", replay, n=3)
print(f"  -> {dt/max(rep.steps_replayed,1)*1e6:.0f} us/replayed-step")
